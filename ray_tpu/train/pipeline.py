"""MPMD pipeline-parallel training over resident stage actors.

The model is split into stages (:class:`StageSpec`); each stage runs as a
resident actor executing a 1F1B microbatch schedule (warmup forwards,
steady-state one-forward-one-backward interleave, drain backwards) over
preallocated :class:`~ray_tpu.graph.channels.ShmChannel` hops — the same
depth-1 mutable-shm transport the compiled actor graphs ride
(``graph/compiled.py``), so per-microbatch cost is one memcpy + condvar
wake per hop with **no per-microbatch RPC or driver involvement**.  The
driver only feeds microbatches into the head channel and reads one
metrics record per *step* from the tail.

Topology per data-parallel replica ``r`` (S stages, M microbatches)::

    driver ──x──▶ stage 0 ──act──▶ stage 1 ─ … ─▶ stage S-1 ──res──▶ driver
    driver ──y────────────────────────────────────▶ stage S-1
              stage 0 ◀──grad── stage 1 ◀─ … ─◀ stage S-1

Backward uses full recompute (``jax.vjp`` of the stage's forward at the
stashed input), and the last stage fuses loss + gradient into one jitted
``value_and_grad`` at its forward slot, so warmup for stage ``i`` is
``min(S-1-i, M)`` and the schedule is deadlock-free on depth-1 channels.
Gradients accumulate across microbatches; the data-parallel allreduce (or
ZeRO reducescatter/allgather via
:class:`~ray_tpu.train.collectives.ZeroShardedOptimizer`) folds into the
stage loop at the step boundary — it rides the quantized collective wire
when ``RT_quantized_collectives`` is on.

This module is deliberately independent of :class:`JaxTrainer`: anything
that wants resident stage actors streaming microbatches (e.g. a Podracer
style RL learner feeding trajectories) can drive a
:class:`PipelineRunner` directly.
"""

from __future__ import annotations

import collections
import dataclasses
import uuid
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ray_tpu.graph.compiled import PipelineStageError

_LOOP_IO_TIMEOUT_S = 600.0  # stage-loop channel ops; driver watches refs


@dataclasses.dataclass
class StageSpec:
    """One pipeline stage: ``init(rng) -> params``,
    ``apply(params, x) -> y``.  ``apply`` must be jit-traceable; backward
    is derived from it via ``jax.vjp`` (full recompute)."""

    init: Callable[..., Any]
    apply: Callable[[Any, Any], Any]
    name: str = ""


@dataclasses.dataclass
class PipelineSpec:
    """Declarative pipeline: stages + schedule + optimizer.

    ``loss(y_pred, y) -> scalar`` is fused with the last stage's forward.
    ``data_parallel`` replicates the whole pipeline R times with gradient
    allreduce across replicas folded into each stage's step boundary;
    ``zero_sharded_state`` switches that allreduce to the ZeRO
    reducescatter → shard-update → allgather form (optimizer state sharded
    1/R per replica).  ``num_steps``/``data_fn`` are consumed by
    ``JaxTrainer.fit`` only — ``PipelineRunner`` users drive ``step()``
    themselves.
    """

    stages: Sequence[StageSpec]
    loss: Callable[[Any, Any], Any]
    num_microbatches: int = 4
    optimizer: str = "sgd"
    learning_rate: float = 0.01
    data_parallel: int = 1
    zero_sharded_state: bool = False
    channel_capacity: int = 4 * 1024 * 1024
    seed: int = 0
    num_steps: int = 1
    data_fn: Optional[Callable[[int], Any]] = None

    def __post_init__(self):
        if not self.stages:
            raise ValueError("PipelineSpec needs at least one stage")
        if self.num_microbatches < 1:
            raise ValueError("num_microbatches must be >= 1")
        if self.data_parallel < 1:
            raise ValueError("data_parallel must be >= 1")
        if self.zero_sharded_state and self.data_parallel < 2:
            raise ValueError(
                "zero_sharded_state shards optimizer state across "
                "data-parallel replicas; it needs data_parallel >= 2")


class _CleanStop(Exception):
    """Input channel closed at a step boundary: normal termination."""


def _host(value):
    """Pytree of device arrays -> pytree of host numpy (wire format)."""
    import jax

    return jax.tree_util.tree_map(np.asarray, jax.device_get(value))


class _PipelineStageActor:
    """Resident stage: builds its jitted programs once, then runs the
    1F1B loop until its input channel closes (clean stop cascades head to
    tail through channel closure)."""

    def __init__(self, stage_blob: bytes, index: int, n_stages: int,
                 num_microbatches: int, seed: int, optimizer: str,
                 learning_rate: float, dp_spec=None):
        import cloudpickle

        fns = cloudpickle.loads(stage_blob)
        self._init_fn = fns["init"]
        self._apply_fn = fns["apply"]
        self._loss_fn = fns.get("loss")
        self._index = index
        self._n_stages = n_stages
        self._M = num_microbatches
        self._seed = seed
        self._opt_kind = optimizer
        self._lr = learning_rate
        self._dp_spec = dp_spec  # (tag, rank, world, zero) | None
        self._is_last = index == n_stages - 1

    # ------------------------------------------------------------- programs
    def _build_fns(self):
        """One jit scope per program, built ONCE per actor lifetime — the
        loop replays them (stable shapes → no retrace per microbatch)."""
        import jax

        apply_fn = self._apply_fn
        fwd = jax.jit(apply_fn)

        def _bwd(p, x, g):
            _, vjp = jax.vjp(apply_fn, p, x)
            return vjp(g)  # (grad_params, grad_x)

        bwd = jax.jit(_bwd)
        fused = None
        if self._is_last:
            loss_fn = self._loss_fn

            def _loss(p, x, y):
                return loss_fn(apply_fn(p, x), y)

            fused = jax.jit(jax.value_and_grad(_loss, argnums=(0, 1)))
        return fwd, bwd, fused

    # ------------------------------------------------------------ exec loop
    def run_pipeline(self, in_ch, out_ch, grad_in_ch, grad_out_ch,
                     label_ch, result_ch):
        """Run steps until ``in_ch`` closes; returns final host params."""
        import jax
        from jax.flatten_util import ravel_pytree

        from ray_tpu.parallel.sharding import _ensure_partitionable_rng
        from ray_tpu.train.collectives import (
            FlatOptimizer,
            ZeroShardedOptimizer,
        )

        # same-seed ⇒ same-params as a single-process reference requires
        # the same PRNG regime (jax < 0.5 defaults it off; driver
        # processes that imported ray_tpu.parallel already flipped it)
        _ensure_partitionable_rng()
        params = _host(self._init_fn(
            jax.random.PRNGKey(self._seed + self._index)))
        fwd, bwd, fused = self._build_fns()
        opt = FlatOptimizer(kind=self._opt_kind, lr=self._lr)
        opt_state = None
        dp_group = zero = None
        if self._dp_spec is not None:
            tag, rank, world, use_zero = self._dp_spec
            from ray_tpu import collective as _coll

            # every replica's stage-i loop starts concurrently → the KV
            # rendezvous for this per-stage group completes
            group_name = f"{tag}:dp:{self._index}"
            _coll.init_collective_group(world, rank, backend="kv",
                                        group_name=group_name)
            dp_group = _coll.get_group_handle(group_name)
            if use_zero:
                zero = ZeroShardedOptimizer(dp_group, opt)

        out_chans = [c for c in (out_ch, grad_out_ch, result_ch)
                     if c is not None]
        step = 0
        try:
            while True:
                try:
                    grads, loss = self._one_step(params, fwd, bwd, fused,
                                                 in_ch, out_ch, grad_in_ch,
                                                 grad_out_ch, label_ch)
                except _CleanStop:
                    break
                pflat, unravel = ravel_pytree(params)
                pflat = np.asarray(pflat)
                gflat = np.asarray(ravel_pytree(grads)[0])
                if zero is not None:
                    new_flat = zero.step(pflat, gflat, average=True)
                else:
                    if dp_group is not None:
                        gflat = np.asarray(
                            dp_group.allreduce(gflat)) / dp_group.world_size
                    if opt_state is None:
                        opt_state = opt.init_state(pflat.size, pflat.dtype)
                    new_flat = opt.update(pflat, gflat, opt_state)
                params = _host(unravel(new_flat))
                step += 1
                if result_ch is not None:
                    result_ch.write({"step": step, "loss": loss},
                                    timeout_s=_LOOP_IO_TIMEOUT_S)
        except BaseException:
            # error stop: close OUR output ends first so blocked neighbors
            # wake with ChannelClosed (cascade) instead of riding out
            # their timeouts, then let the loop ref carry the real error
            for c in out_chans:
                c.close()
            raise
        for c in out_chans:  # clean stop: cascade closure downstream
            c.close()
        return params

    def _one_step(self, params, fwd, bwd, fused, in_ch, out_ch, grad_in_ch,
                  grad_out_ch, label_ch):
        """One 1F1B step over M microbatches; returns (mean grads pytree,
        mean loss or None).  ChannelClosed on the FIRST read of the step
        is a clean stop; anywhere else it propagates as an error."""
        import jax

        from ray_tpu.graph.channels import ChannelClosed

        M = self._M
        warmup = min(self._n_stages - 1 - self._index, M)
        stash = collections.deque()
        acc = [None]
        loss_sum = [0.0]
        first = [True]

        def add(g):
            acc[0] = g if acc[0] is None else jax.tree_util.tree_map(
                lambda a, b: a + b, acc[0], g)

        def forward():
            try:
                x = in_ch.read(timeout_s=_LOOP_IO_TIMEOUT_S)
            except ChannelClosed:
                if first[0]:
                    raise _CleanStop from None
                raise
            first[0] = False
            if self._is_last:
                y = label_ch.read(timeout_s=_LOOP_IO_TIMEOUT_S)
                loss, (gp, gx) = fused(params, x, y)
                loss_sum[0] += float(loss)
                add(gp)
                if grad_out_ch is not None:
                    grad_out_ch.write(_host(gx),
                                      timeout_s=_LOOP_IO_TIMEOUT_S)
            else:
                yv = fwd(params, x)
                stash.append(x)
                out_ch.write(_host(yv), timeout_s=_LOOP_IO_TIMEOUT_S)

        def backward():
            if self._is_last:
                return  # fused into the forward slot
            g = grad_in_ch.read(timeout_s=_LOOP_IO_TIMEOUT_S)
            gp, gx = bwd(params, stash.popleft(), g)
            add(gp)
            if grad_out_ch is not None:
                grad_out_ch.write(_host(gx), timeout_s=_LOOP_IO_TIMEOUT_S)

        for _ in range(warmup):
            forward()
        for _ in range(M - warmup):
            forward()
            backward()
        for _ in range(warmup):
            backward()

        import jax as _jax  # grads averaged over microbatches

        grads = _jax.tree_util.tree_map(lambda a: np.asarray(a) / M, acc[0])
        loss = loss_sum[0] / M if self._is_last else None
        return grads, loss


class PipelineRunner:
    """Driver handle: creates channels + stage actors, starts the exec
    loops, then ``step(xs, ys)`` streams one step's microbatches and
    returns the step metrics.  ``finish()`` closes the head channels
    (clean-stop cascade) and returns the final stage params.

    A stage actor killed mid-pipeline surfaces as
    :class:`~ray_tpu.graph.compiled.PipelineStageError` from ``step()``
    within the caller's deadline — channel waits run in short slices with
    the stage loop refs polled between slices, exactly like the compiled
    DAG's ``execute()``."""

    def __init__(self, spec: PipelineSpec, actor_options: Optional[dict] = None):
        import cloudpickle

        import ray_tpu
        from ray_tpu.graph.channels import ShmChannel

        self.spec = spec
        S = len(spec.stages)
        R = spec.data_parallel
        tag = uuid.uuid4().hex[:10]
        self._tag = tag
        cap = spec.channel_capacity
        self._channels: List[ShmChannel] = []

        def make(name):
            ch = ShmChannel(f"/rtpp_{tag}_{name}", capacity=cap,
                            num_readers=1)
            ch._handle()  # create the segment before any actor opens it
            self._channels.append(ch)
            return ch

        self._x = [make(f"x{r}") for r in range(R)]
        self._y = [make(f"y{r}") for r in range(R)]
        self._res = [make(f"res{r}") for r in range(R)]
        acts = [[make(f"a{r}_{i}") for i in range(S - 1)] for r in range(R)]
        grads = [[make(f"g{r}_{i}") for i in range(S - 1)] for r in range(R)]

        self._actors = []
        self._loop_refs = []
        remote_cls = ray_tpu.remote(_PipelineStageActor)
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        for r in range(R):
            for i, stage in enumerate(spec.stages):
                blob = cloudpickle.dumps(
                    {"init": stage.init, "apply": stage.apply,
                     "loss": spec.loss if i == S - 1 else None})
                dp_spec = (tag, r, R, spec.zero_sharded_state) \
                    if R > 1 else None
                handle = remote_cls.options(**opts).remote(
                    blob, i, S, spec.num_microbatches, spec.seed,
                    spec.optimizer, spec.learning_rate, dp_spec)
                self._actors.append(handle)
                in_ch = self._x[r] if i == 0 else acts[r][i - 1]
                out_ch = acts[r][i] if i < S - 1 else None
                grad_in = grads[r][i] if i < S - 1 else None
                grad_out = grads[r][i - 1] if i > 0 else None
                label = self._y[r] if i == S - 1 else None
                res = self._res[r] if i == S - 1 else None
                self._loop_refs.append(handle.run_pipeline.remote(
                    in_ch, out_ch, grad_in, grad_out, label, res))
        self._step = 0
        self._done = False

    # ----------------------------------------------------- failure watching
    def _check_stage_loops(self):
        if not self._loop_refs:
            return
        import ray_tpu

        done, _ = ray_tpu.wait(self._loop_refs,
                               num_returns=len(self._loop_refs), timeout=0)
        for ref in done:
            try:
                ray_tpu.get(ref)
            except Exception as e:  # noqa: BLE001 — actor death/loop error
                raise PipelineStageError(
                    f"pipeline stage exec loop failed: "
                    f"{type(e).__name__}: {e}") from e

    def _watched(self, op, timeout_s: float):
        """Run a channel read/write in short slices, polling the stage
        loop refs between slices; a dead stage raises typed within the
        deadline instead of hanging the channel wait."""
        from ray_tpu.common.retry import Deadline

        deadline = Deadline(timeout_s)
        while True:
            try:
                return op(deadline.remaining(cap=0.2) or 0.0)
            except TimeoutError:
                if deadline.expired():
                    raise
                self._check_stage_loops()

    # ----------------------------------------------------------------- step
    def step(self, xs: Sequence, ys: Sequence,
             timeout_s: float = 120.0) -> dict:
        """Feed one step: ``xs``/``ys`` hold ``num_microbatches *
        data_parallel`` microbatch arrays (replica-major: replica r gets
        ``xs[r*M:(r+1)*M]``).  Returns ``{"step", "loss"}`` with the loss
        averaged across replicas."""
        if self._done:
            raise RuntimeError("pipeline already finished")
        M = self.spec.num_microbatches
        R = self.spec.data_parallel
        if len(xs) != M * R or len(ys) != M * R:
            raise ValueError(
                f"need {M * R} microbatches (M={M} x R={R}), got "
                f"{len(xs)}/{len(ys)}")
        try:
            for m in range(M):
                for r in range(R):
                    x, y = np.asarray(xs[r * M + m]), np.asarray(ys[r * M + m])
                    self._watched(
                        lambda t, c=self._x[r], v=x: c.write(v, timeout_s=t),
                        timeout_s)
                    self._watched(
                        lambda t, c=self._y[r], v=y: c.write(v, timeout_s=t),
                        timeout_s)
            losses = []
            for r in range(R):
                rec = self._watched(
                    lambda t, c=self._res[r]: c.read(timeout_s=t), timeout_s)
                losses.append(rec["loss"])
        except PipelineStageError:
            self.shutdown()
            raise
        self._step += 1
        return {"step": self._step, "loss": float(np.mean(losses))}

    # --------------------------------------------------------------- finish
    def finish(self, timeout_s: float = 120.0) -> List[Any]:
        """Close the head channels (clean-stop cascades tail-ward), join
        the stage loops, and return replica 0's per-stage final params."""
        import ray_tpu

        if self._done:
            raise RuntimeError("pipeline already finished")
        self._done = True
        for ch in self._x + self._y:
            ch.close()
        try:
            all_params = ray_tpu.get(self._loop_refs)
        except Exception as e:  # noqa: BLE001 — a stage died during drain
            self.shutdown()
            raise PipelineStageError(
                f"pipeline stage failed during drain: "
                f"{type(e).__name__}: {e}") from e
        S = len(self.spec.stages)
        return list(all_params[:S])  # replica 0 is the first S loop refs

    def shutdown(self):
        """Idempotent teardown: close + unlink channels, kill actors."""
        import ray_tpu

        self._done = True
        for ch in self._channels:
            ch.close()
            ch.unlink()
        self._channels = []
        for handle in self._actors:
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001 — already dead
                pass
        self._actors = []
        self._loop_refs = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
