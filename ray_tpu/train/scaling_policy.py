"""Scaling policies: how many workers the trainer gangs up, and when to
resize a running gang.

Reference: ``python/ray/train/v2/_internal/execution/scaling_policy/``
(FixedScalingPolicy + the pluggable elastic interface consulted by the
TrainController loop). TPU framing: a resize is a gang RESTART at a new
world size — SPMD programs are compiled for a fixed mesh, so elasticity
means "restart from the latest checkpoint on a bigger/smaller mesh", not
adding workers to a live mesh. The policy decides sizes; the trainer
owns the restart mechanics it already has for failures.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, Optional


@dataclasses.dataclass
class ResizeDecision:
    num_workers: int
    reason: str = ""


NOOP = None  # decide() returns None for "keep running as-is"


def _feasible_workers(bundle: Dict[str, float],
                      available: Dict[str, float]) -> int:
    """How many copies of `bundle` fit in `available` resources."""
    n = math.inf
    for res, qty in bundle.items():
        if qty <= 0:
            continue
        n = min(n, int(available.get(res, 0.0) // qty))
    return 0 if n is math.inf else int(n)


class FixedScalingPolicy:
    """Always the configured size; failures restart at the same size
    (the v1 behavior the trainer had built in)."""

    WATCHES_CAPACITY = False  # trainer skips capacity polling entirely

    def __init__(self, num_workers: int):
        self.num_workers = num_workers

    def initial_size(self, bundle, available) -> int:
        del bundle, available
        return self.num_workers

    def size_after_failure(self, bundle, available) -> int:
        del bundle, available
        return self.num_workers

    def decide(self, current_size: int, bundle, available):
        return NOOP


class ElasticScalingPolicy:
    """Run with whatever fits between min_workers and max_workers.

    - start: largest feasible size <= max (>= min or scheduling blocks)
    - failure: shrink to what is feasible NOW instead of insisting on
      the lost size (a dead node must not wedge training)
    - while running: if capacity for >= `upscale_step` more workers sits
      idle for `upscale_patience_s`, request an upscale restart from the
      latest checkpoint (cheap with frequent checkpoints; the trainer
      does the restart)
    """

    WATCHES_CAPACITY = True

    def __init__(self, min_workers: int, max_workers: int, *,
                 upscale_step: int = 1, upscale_patience_s: float = 5.0):
        if min_workers < 1 or max_workers < min_workers:
            raise ValueError("need 1 <= min_workers <= max_workers")
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.upscale_step = upscale_step
        self.upscale_patience_s = upscale_patience_s
        self._surplus_since: Optional[float] = None

    def _clamp(self, n: int) -> int:
        return max(self.min_workers, min(self.max_workers, n))

    def initial_size(self, bundle, available) -> int:
        return self._clamp(_feasible_workers(bundle, available))

    def size_after_failure(self, bundle, available) -> int:
        # the gang is down: its resources read as available again
        return self._clamp(_feasible_workers(bundle, available))

    def decide(self, current_size: int, bundle, available):
        if current_size >= self.max_workers:
            self._surplus_since = None
            return NOOP
        headroom = _feasible_workers(bundle, available)  # beyond the gang
        target = min(self.max_workers, current_size + headroom)
        if target - current_size < self.upscale_step:
            self._surplus_since = None
            return NOOP
        now = time.monotonic()
        if self._surplus_since is None:
            self._surplus_since = now
            return NOOP
        if now - self._surplus_since < self.upscale_patience_s:
            return NOOP
        self._surplus_since = None
        return ResizeDecision(
            target, f"idle capacity for {target - current_size} more workers")
