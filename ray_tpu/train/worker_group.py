"""Worker group: gang-scheduled training-worker actors.

Reference: ``python/ray/train/v2/_internal/execution/worker_group/
worker_group.py:99`` (actor fleet in a placement group, rank assignment,
context injection) and ``worker.py:116`` (RayTrainWorker: run train_fn in a
thread, poll status). TPU specifics: bundles carry TPU chips, placement
uses SLICE_PACK so a group lands on one ICI slice, and rank 0 allocates
the JAX coordinator port for the mesh bootstrap.
"""

from __future__ import annotations

import socket
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional


class TrainWorker:
    """Actor harness around the user's ``train_fn`` (one per rank)."""

    def __init__(self):
        self._ctx = None
        self._thread: Optional[threading.Thread] = None
        self._status = "idle"
        self._error: Optional[str] = None

    def get_coordinator_address(self) -> str:
        """Rank 0: pick a free port for jax.distributed.initialize."""
        # UDP-connect trick: gethostbyname(hostname) often resolves to
        # 127.0.1.1 (unreachable from other hosts).
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as probe:
            try:
                probe.connect(("8.8.8.8", 80))
                host = probe.getsockname()[0]
            except OSError:
                host = "127.0.0.1"
        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        return f"{host}:{port}"

    def setup(self, ctx_kwargs: Dict[str, Any]) -> bool:
        from ray_tpu.train.checkpoint import Checkpoint
        from ray_tpu.train.context import TrainContext, _set_context

        resume = ctx_kwargs.pop("resume_from_path", None)
        datasets = ctx_kwargs.pop("datasets", None)
        ctx = TrainContext(**ctx_kwargs)
        if datasets:
            ctx._datasets = dict(datasets)
        if resume:
            ctx.resume_from = Checkpoint(resume)
        self._ctx = ctx
        _set_context(ctx)
        return True

    def run(self, train_fn: Callable, config: Optional[dict]) -> bool:
        if self._thread is not None:
            raise RuntimeError("worker already running")
        self._status = "running"

        def target():
            try:
                if _fn_wants_config(train_fn):
                    train_fn(config or {})
                else:
                    train_fn()
                self._status = "finished"
            except BaseException:  # noqa: BLE001 — report, don't die
                self._error = traceback.format_exc()
                self._status = "error"

        self._thread = threading.Thread(target=target, daemon=True,
                                        name="train_fn")
        self._thread.start()
        return True

    def poll(self) -> Dict[str, Any]:
        # Status BEFORE draining: a 'finished' status then guarantees every
        # report (train_fn pushes before the thread flips the status) was
        # included in this drain — no lost final checkpoint.
        status, error = self._status, self._error
        reports = self._ctx._drain_reports() if self._ctx else []
        return {"status": status, "error": error, "reports": reports}

    def stop(self) -> bool:
        if self._ctx is not None:
            self._ctx._stop_event.set()
        return True

    def shutdown_worker(self) -> bool:
        return True


def _fn_wants_config(fn) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return True
    return len(sig.parameters) >= 1


class WorkerGroup:
    """Controller-side handle on the actor fleet + its placement group."""

    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK"):
        self.num_workers = num_workers
        self.resources_per_worker = dict(resources_per_worker)
        self.placement_strategy = placement_strategy
        self.workers: List[Any] = []
        self.pg = None

    def start(self, *, experiment_name: str, storage_path: str,
              train_fn: Callable, config: Optional[dict],
              resume_from_path: Optional[str] = None,
              dataset_shards: Optional[Dict[str, list]] = None,
              pg_timeout: float = 60.0) -> None:
        import ray_tpu

        bundles = [dict(self.resources_per_worker)
                   for _ in range(self.num_workers)]
        self.pg = ray_tpu.placement_group(bundles,
                                          strategy=self.placement_strategy)
        if not self.pg.ready(timeout=pg_timeout):
            raise TimeoutError(
                f"placement group for {self.num_workers} workers "
                f"({self.resources_per_worker} each) not schedulable in "
                f"{pg_timeout}s")

        from ray_tpu.core_worker.placement_group import (
            PlacementGroupSchedulingStrategy,
        )

        remote_cls = ray_tpu.remote(TrainWorker)
        self.workers = [
            remote_cls.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self.pg,
                    placement_group_bundle_index=i),
                resources=dict(self.resources_per_worker),
            ).remote()
            for i in range(self.num_workers)
        ]

        coordinator = None
        if self.num_workers > 1:
            coordinator = ray_tpu.get(
                self.workers[0].get_coordinator_address.remote())

        import uuid

        run_id = uuid.uuid4().hex[:12]  # fresh per gang instance
        setups = []
        for rank, w in enumerate(self.workers):
            setups.append(w.setup.remote({
                "run_id": run_id,
                "experiment_name": experiment_name,
                "world_rank": rank,
                "world_size": self.num_workers,
                "local_rank": 0,
                "local_world_size": 1,
                "node_rank": rank,
                "storage_path": storage_path,
                "coordinator": coordinator,
                "resume_from_path": resume_from_path,
                "datasets": ({name: shards[rank] for name, shards
                              in dataset_shards.items()}
                             if dataset_shards else None),
            }))
        ray_tpu.get(setups)
        ray_tpu.get([w.run.remote(train_fn, config) for w in self.workers])

    def poll(self, timeout: float = 30.0) -> List[Dict[str, Any]]:
        import ray_tpu

        return ray_tpu.get([w.poll.remote() for w in self.workers],
                           timeout=timeout)

    def shutdown(self, grace_s: float = 5.0):
        import ray_tpu

        # Deliver the cooperative stop (should_stop()) before killing, so
        # workers can flush final state; best-effort with a bounded wait.
        try:
            ray_tpu.get([w.stop.remote() for w in self.workers],
                        timeout=grace_s)
        except Exception:  # noqa: BLE001 — dead workers can't ack
            pass
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
        if self.pg is not None:
            try:
                ray_tpu.remove_placement_group(self.pg)
            except Exception:  # noqa: BLE001
                pass
        self.workers, self.pg = [], None
