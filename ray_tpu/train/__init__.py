"""Train library — Train-v2-shaped distributed training on TPU.

Reference architecture (SURVEY.md §3.4, reference ``python/ray/train/v2/``):
controller state-machine loop + gang-scheduled worker group + scaling /
failure policies + checkpoint manager. The TPU divergence: workers don't
wire a torch process group — rank 0 publishes a JAX coordinator address via
the internal KV and every worker joins the global device mesh
(``jax.distributed``), after which all parallelism is in-program GSPMD.
"""

from ray_tpu.train.checkpoint import (  # noqa: F401
    Checkpoint,
    CheckpointConfig,
    CheckpointManager,
)
from ray_tpu.train.collectives import (  # noqa: F401
    FlatOptimizer,
    ZeroShardedOptimizer,
    barrier,
    broadcast_from_rank_zero,
)
from ray_tpu.train.context import (  # noqa: F401
    TrainContext,
    checkpoint_dir,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.pipeline import (  # noqa: F401
    PipelineRunner,
    PipelineSpec,
    StageSpec,
)
from ray_tpu.train.scaling_policy import (  # noqa: F401
    ElasticScalingPolicy,
    FixedScalingPolicy,
)
from ray_tpu.train.torch_trainer import (  # noqa: F401
    TorchTrainer,
    prepare_data_loader,
    prepare_model,
)
from ray_tpu.train.trainer import (  # noqa: F401
    FailureConfig,
    JaxTrainer,
    Result,
    RunConfig,
    ScalingConfig,
    TrainingFailedError,
)

from ray_tpu.util.usage import record_library_usage as _record_usage
_record_usage("train")
del _record_usage
