"""TorchTrainer: the reference's flagship trainer surface on this
runtime.

Reference: ``python/ray/train/torch/`` (``TorchConfig:36``,
``_TorchBackend:153``, ``config.py:66-151`` ``_setup_torch_process_
group``) + ``train/torch/train_loop_utils.py`` (``prepare_model``,
``prepare_data_loader``). Users migrating from the reference keep their
``train_loop_per_worker`` verbatim: the trainer gang-schedules workers,
wires a ``torch.distributed`` gloo process group through the same KV
rendezvous the JAX path uses, and tears it down afterwards.

Positioning note (why gloo, on a TPU framework): torch here is the
CPU-side ecosystem bridge — preprocessing loops, reference models,
parity tests. The accelerator path of this framework is JAX/XLA
(:class:`~ray_tpu.train.trainer.JaxTrainer`); there is deliberately no
NCCL/CUDA wiring.
"""

from __future__ import annotations

import logging
import socket
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.trainer import JaxTrainer

logger = logging.getLogger(__name__)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _setup_process_group() -> bool:
    """Initialize torch.distributed (gloo) across the gang; rank 0
    binds the store port and publishes it (reference:
    ``_setup_torch_process_group``). No-op for world_size == 1."""
    from ray_tpu import train

    ctx = train.get_context()
    world = ctx.get_world_size()
    if world <= 1:
        return False
    import torch.distributed as dist

    if ctx.get_world_rank() == 0:
        addr = f"{socket.gethostbyname(socket.gethostname())}:{_free_port()}"
        train.broadcast_from_rank_zero(addr)
    else:
        addr = train.broadcast_from_rank_zero(None)
    logger.info("torch pg init rank=%d world=%d addr=%s",
                ctx.get_world_rank(), world, addr)
    dist.init_process_group(
        backend="gloo", init_method=f"tcp://{addr}",
        rank=ctx.get_world_rank(), world_size=world)
    return True


def prepare_model(model):
    """DDP-wrap when distributed (reference ``train.torch.prepare_model``
    — minus device moves: this backend is CPU/gloo by design)."""
    import torch.distributed as dist

    if dist.is_available() and dist.is_initialized() \
            and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


class _EpochAdvancingLoader:
    """Iterating advances the DistributedSampler epoch first, so each
    pass over a shuffled loader sees a fresh shard order (the
    ``sampler.set_epoch`` contract the reference wires up for users)."""

    def __init__(self, loader, sampler):
        self._loader = loader
        self._sampler = sampler
        self._epoch = 0

    def __iter__(self):
        self._sampler.set_epoch(self._epoch)
        self._epoch += 1
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)

    def __getattr__(self, name):
        return getattr(self._loader, name)


def prepare_data_loader(data_loader):
    """Shard a DataLoader across workers with a DistributedSampler
    (reference ``train.torch.prepare_data_loader``): the incoming
    loader's shuffle intent (inferred from its sampler, as the
    reference does), batching, worker, and collate settings are
    preserved; each epoch re-shuffles via ``set_epoch``."""
    import torch.distributed as dist

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    if data_loader.batch_size is None:
        # batch_sampler-driven loaders have no batch_size to rebuild
        # with; sharding one automatically would silently change its
        # batching. The user shards their batch_sampler themselves.
        raise ValueError(
            "prepare_data_loader cannot shard a DataLoader built with "
            "batch_sampler=...; construct the DistributedSampler-aware "
            "batch_sampler yourself")
    import torch.utils.data as tud
    from torch.utils.data.distributed import DistributedSampler

    shuffle = not isinstance(data_loader.sampler, tud.SequentialSampler)
    sampler = DistributedSampler(data_loader.dataset, shuffle=shuffle)
    loader = tud.DataLoader(
        data_loader.dataset, batch_size=data_loader.batch_size,
        sampler=sampler, num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        pin_memory=data_loader.pin_memory,
        drop_last=data_loader.drop_last,
        worker_init_fn=data_loader.worker_init_fn,
        generator=data_loader.generator)
    return _EpochAdvancingLoader(loader, sampler)


class TorchTrainer(JaxTrainer):
    """Same controller/worker-group/checkpoint machinery as JaxTrainer;
    only the per-worker bootstrap differs."""

    def __init__(self, train_loop_per_worker: Callable,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 **kwargs: Any):
        def wrapped(config):
            started = _setup_process_group()
            try:
                train_loop_per_worker(config)
            finally:
                if started:
                    import torch.distributed as dist

                    try:
                        dist.destroy_process_group()
                    except Exception:  # noqa: BLE001 — teardown best-effort
                        pass

        super().__init__(wrapped, train_loop_config=train_loop_config,
                         **kwargs)
