"""Worker-side training context + report API.

Reference: ``python/ray/train/v2/api/context.py`` (TrainContext) and
``ray.train.report`` — metrics/checkpoint flow from workers to the
controller. TPU addition: the context carries the JAX distributed-mesh
bootstrap info (coordinator address, process id/count) so ``train_fn``
can join the global device mesh with one call.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint

_context_lock = threading.Lock()
_context: Optional["TrainContext"] = None


@dataclasses.dataclass
class TrainContext:
    experiment_name: str
    world_rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    node_rank: int
    storage_path: str
    # JAX mesh bootstrap (multi-host SPMD): rank 0's RPC coordinator.
    coordinator: Optional[str] = None
    resume_from: Optional[Checkpoint] = None
    # unique per gang INSTANCE (fresh on every restart/resize): keys
    # collective rendezvous namespaces so attempts never see stale state
    run_id: str = ""

    # per-worker Data shards injected by the trainer (name -> DataIterator)
    _datasets: dict = dataclasses.field(default_factory=dict, repr=False)
    # populated by the worker harness
    _reports: List[dict] = dataclasses.field(default_factory=list)
    _report_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False)
    _stop_event: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False)

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_run_id(self) -> str:
        return self.run_id

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_storage_path(self) -> str:
        return self.storage_path

    def get_dataset_shard(self, name: str = "train"):
        """This worker's per-rank DataIterator from the trainer's
        ``datasets`` (reference: ``ray.train.get_dataset_shard``): fed by
        ONE streaming execution via ``Dataset.streaming_split`` — blocks
        arrive as produced, with backpressure, re-iterable per epoch."""
        if name not in self._datasets:
            raise KeyError(
                f"no dataset shard {name!r}; trainer datasets: "
                f"{sorted(self._datasets)}")
        return self._datasets[name]

    def get_checkpoint(self) -> Optional[Checkpoint]:
        """Checkpoint to resume from (set on restore / failure restart)."""
        return self.resume_from

    def should_stop(self) -> bool:
        """Cooperative-cancellation flag (elastic resize / shutdown)."""
        return self._stop_event.is_set()

    def init_jax_distributed(self) -> None:
        """Join the global JAX mesh (multi-host SPMD). No-op when
        single-process (tests, one-host runs).

        On TPU pods this is ``jax.distributed.initialize`` against rank 0's
        coordinator (the WorkerGroup picks the address and injects it into
        every rank's context). On CPU (multi-process tests, DCN-only
        clusters) the gloo collectives backend is enabled so cross-process
        psum/all-gather work the same way.
        """
        if self.world_size == 1 or self.coordinator is None:
            return
        # Must precede the first jax import in this process.
        if "jax" not in __import__("sys").modules:
            os.environ.setdefault("JAX_CPU_COLLECTIVES", "gloo")
        import jax

        jax.distributed.initialize(
            coordinator_address=self.coordinator,
            num_processes=self.world_size,
            process_id=self.world_rank)

    # -------------------------------------------------- report plumbing
    def _push_report(self, metrics: Dict[str, Any],
                     checkpoint: Optional[Checkpoint]):
        with self._report_lock:
            self._reports.append({
                "metrics": dict(metrics),
                "checkpoint_path": checkpoint.path if checkpoint else None,
            })

    def _drain_reports(self) -> List[dict]:
        with self._report_lock:
            out, self._reports = self._reports, []
            return out


def get_dataset_shard(name: str = "train"):
    """This worker's Data shard (reference:
    ``ray.train.get_dataset_shard``)."""
    return get_context().get_dataset_shard(name)


def get_context() -> TrainContext:
    with _context_lock:
        if _context is None:
            raise RuntimeError(
                "ray_tpu.train.get_context() called outside a training "
                "worker")
        return _context


def _set_context(ctx: Optional[TrainContext]):
    global _context
    with _context_lock:
        _context = ctx


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (+ checkpoint) from inside ``train_fn``.

    Like the reference, the checkpoint must already be persisted (written
    to a directory under the storage path — e.g. via
    ``Checkpoint.from_pytree``); report only registers it.
    """
    get_context()._push_report(metrics, checkpoint)


def checkpoint_dir(step: int) -> str:
    """Canonical per-step checkpoint directory for this run (rank-shared)."""
    ctx = get_context()
    return os.path.join(ctx.storage_path, f"checkpoint_{step:08d}")
