"""Client server — head-side half of the ``ray://`` protocol.

Reference: ``python/ray/util/client/server/proxier.py`` (one server-side
driver PROCESS per client session, so sessions get their own job, clean
teardown, and no shared interpreter state). Here:

- :class:`ClientServer` listens on the advertised client port; a
  ``new_session`` RPC forks a session driver subprocess
  (``session_main.py``) which runs ``ray_tpu.init(address=gcs)`` as a real
  driver and serves the session API on its own port.
- The client then talks to its session driver directly (same host as the
  head — the only address a NAT'd client can reach is the head anyway, and
  per-session ports keep the proxy out of the data path).
- Sessions die with their connection: the driver subprocess exits when the
  client stops pinging (heartbeat timeout), releasing its job and refs.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time
from typing import Dict, Tuple

from ray_tpu.rpc.rpc import IoContext, RpcServer

logger = logging.getLogger(__name__)

DEFAULT_CLIENT_PORT = 10001


class ClientServer:
    def __init__(self, gcs_address: Tuple[str, int],
                 host: str = "127.0.0.1", port: int = 0):
        self._gcs_address = tuple(gcs_address)
        self._host = host  # session drivers bind here too: the client must
        # be able to reach their per-session ports directly
        self.server = RpcServer(host, port)
        self.server.register("new_session", self.h_new_session)
        self.server.register("end_session", self.h_end_session)
        self.server.register("ping", self.h_ping)
        self._sessions: Dict[str, subprocess.Popen] = {}
        self._io = IoContext.current()

    def start(self):
        self.server.start()
        logger.info("client server at %s", self.server.address)

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    async def h_ping(self):
        return True

    def _reap(self):
        """Collect exited session drivers (heartbeat-timeout exits would
        otherwise sit as zombies for the server's lifetime)."""
        for sid in list(self._sessions):
            if self._sessions[sid].poll() is not None:
                del self._sessions[sid]

    async def h_new_session(self, session_id: str,
                            runtime_env: dict = None):
        import asyncio

        self._reap()
        env = dict(os.environ)
        env["RT_ADDRESS"] = f"{self._gcs_address[0]}:{self._gcs_address[1]}"
        env["RT_CLIENT_SESSION_ID"] = session_id
        env["RT_CLIENT_SESSION_HOST"] = self._host
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        if pkg_root not in env.get("PYTHONPATH", "").split(os.pathsep):
            env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                                 if env.get("PYTHONPATH") else pkg_root)
        if runtime_env:
            import json

            env["RT_JOB_RUNTIME_ENV"] = json.dumps(runtime_env)
        from ray_tpu.common.tpu_detect import defer_tpu_preload

        env = defer_tpu_preload(env)
        proc = await asyncio.to_thread(
            subprocess.Popen,
            [sys.executable, "-m", "ray_tpu.client.session_main"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
        self._sessions[session_id] = proc
        # the session driver prints its serving address on the first line
        line = await asyncio.to_thread(proc.stdout.readline)
        try:
            tag, host, port = line.decode().split()
            assert tag == "SESSION_READY"
        except Exception:  # noqa: BLE001
            proc.kill()
            return {"ok": False, "error": f"session driver failed: {line!r}"}
        return {"ok": True, "address": (host, int(port))}

    async def h_end_session(self, session_id: str):
        import asyncio

        proc = self._sessions.pop(session_id, None)
        if proc is not None:
            if proc.poll() is None:
                proc.terminate()
            await asyncio.to_thread(self._wait_reap, proc)
        self._reap()
        return True

    @staticmethod
    def _wait_reap(proc, timeout: float = 10.0):
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5.0)

    def stop(self):
        for proc in self._sessions.values():
            if proc.poll() is None:
                proc.terminate()
        for proc in self._sessions.values():
            self._wait_reap(proc)
        self._sessions.clear()
        self.server.stop()
