"""Session driver — the server-side driver backing ONE ray:// client.

Reference: the per-client "server-side driver" the proxier spawns
(``python/ray/util/client/server/server.py`` + proxier). It joins the
cluster as a normal driver (so tasks/actors it creates belong to its own
job and die with it) and serves the session RPC surface the thin client
speaks. ObjectRefs cross the wire as opaque ids via pickle persistent_id —
nested refs inside arbitrary argument structures round-trip losslessly.
"""

from __future__ import annotations

import asyncio
import io
import os
import pickle
import threading
import time
from typing import Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.core_worker.reference import ObjectRef
from ray_tpu.rpc.rpc import RpcServer

HEARTBEAT_TIMEOUT_S = 60.0


class _RefPickler(cloudpickle.CloudPickler):
    """Server->client: ObjectRefs become persistent ids."""

    def persistent_id(self, obj):
        if isinstance(obj, ObjectRef):
            return ("rt_ref", obj.object_id.binary())
        return None


def _dumps_with_refs(value) -> bytes:
    buf = io.BytesIO()
    _RefPickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(value)
    return buf.getvalue()


class _RefUnpickler(pickle.Unpickler):
    """Client->server: persistent ids resolve to live ObjectRefs."""

    def __init__(self, f, refs: Dict[bytes, ObjectRef]):
        super().__init__(f)
        self._refs = refs

    def persistent_load(self, pid):
        tag, raw = pid
        if tag != "rt_ref":
            raise pickle.UnpicklingError(f"unknown persistent id {tag!r}")
        return self._refs[raw]


class SessionDriver:
    def __init__(self):
        host = os.environ.get("RT_CLIENT_SESSION_HOST", "127.0.0.1")
        # method names collide with core-service schemas (create_actor etc.)
        # but carry a different contract: skip wire-schema validation
        self.server = RpcServer(host, 0, validate_schemas=False)
        # every ref the client holds is pinned here until released — the
        # client-side refcount is authoritative (reference client ref
        # counting), the server keeps the object alive meanwhile
        self._refs: Dict[bytes, ObjectRef] = {}
        self._actors: Dict[bytes, ray_tpu.api.ActorHandle] = {}
        self._pgs: Dict[bytes, object] = {}       # raw pg id -> PG
        self._fns: Dict[bytes, object] = {}       # fn blob hash -> callable
        self._last_heartbeat = time.monotonic()
        for name in ("put", "get", "wait", "submit", "submit_named",
                     "create_actor", "create_named_actor",
                     "actor_call", "kill_actor", "get_named_actor", "cancel",
                     "release", "cluster_resources", "available_resources",
                     "nodes", "heartbeat",
                     "create_placement_group", "placement_group_ready",
                     "remove_placement_group"):
            self.server.register(name, getattr(self, f"h_{name}"))

    # ------------------------------------------------------------- helpers
    def _loads(self, blob: bytes):
        return _RefUnpickler(io.BytesIO(blob), self._refs).load()

    def _track(self, ref: ObjectRef) -> bytes:
        raw = ref.object_id.binary()
        self._refs[raw] = ref
        return raw

    def _fn(self, fn_blob: bytes):
        # keyed by the blob itself: a 64-bit hash() collision would
        # silently run the WRONG function
        fn = self._fns.get(fn_blob)
        if fn is None:
            fn = cloudpickle.loads(fn_blob)
            self._fns[fn_blob] = fn
        return fn

    # ------------------------------------------------------------ handlers
    async def h_heartbeat(self):
        self._last_heartbeat = time.monotonic()
        return True

    async def h_put(self, blob: bytes):
        # sync API calls park the shared IO loop on themselves: to_thread
        ref = await asyncio.to_thread(lambda: ray_tpu.put(self._loads(blob)))
        return self._track(ref)

    async def h_get(self, raw_ids: List[bytes],
                    timeout_s: Optional[float]):
        refs = [self._refs[r] for r in raw_ids]

        def do():
            try:
                values = ray_tpu.get(refs, timeout=timeout_s)
                if len(refs) == 1:
                    values = [values] if not isinstance(values, list) \
                        else values
                return {"ok": True,
                        "values": [_dumps_with_refs(v) for v in values]}
            except Exception as e:  # noqa: BLE001
                return {"ok": False, "error": _dumps_with_refs(e)}

        return await asyncio.to_thread(do)

    async def h_wait(self, raw_ids: List[bytes], num_returns: int,
                     timeout_s: Optional[float]):
        refs = [self._refs[r] for r in raw_ids]
        ready, not_ready = await asyncio.to_thread(
            ray_tpu.wait, refs, num_returns=num_returns, timeout=timeout_s)
        ready_set = {r.object_id.binary() for r in ready}
        return [r for r in raw_ids if r in ready_set]

    # xlang argument convention: a non-Python driver (cpp api.h) encodes
    # an actor handle as {"__rt_actor_handle__": raw_id} — rebuilt into a
    # live handle here so C++ can pass actors to Python tasks/actors
    # (reference: cross-language actor handle passing).
    _HANDLE_KEY = "__rt_actor_handle__"

    def _revive_handles(self, x):
        if isinstance(x, dict):
            if set(x) == {self._HANDLE_KEY}:
                raw = x[self._HANDLE_KEY]
                handle = self._actors.get(raw)
                if handle is None:
                    from ray_tpu.common.ids import ActorID
                    from ray_tpu.core_worker.actor import ActorHandle

                    handle = ActorHandle(ActorID(raw))
                return handle
            return {k: self._revive_handles(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            out = [self._revive_handles(v) for v in x]
            return type(x)(out) if isinstance(x, tuple) else out
        return x

    def _xlate_opts(self, opts: dict) -> dict:
        """Translate the xlang opts dict: a raw placement-group id (+
        bundle_index) becomes the Python scheduling strategy."""
        opts = dict(opts or {})
        pg_raw = opts.pop("placement_group", None)
        if pg_raw is not None:
            from ray_tpu.common.task_spec import PlacementGroupStrategy

            pg = self._pgs[pg_raw]
            opts["scheduling_strategy"] = PlacementGroupStrategy(
                pg.id, int(opts.pop("bundle_index", 0)))
        return opts

    async def _do_submit(self, fn, args_blob: bytes, opts: dict):
        args, kwargs = self._loads(args_blob)
        args = self._revive_handles(args)
        kwargs = self._revive_handles(kwargs)
        opts = self._xlate_opts(opts)
        rf = ray_tpu.remote(fn)
        if opts:
            rf = rf.options(**opts)

        def do():
            out = rf.remote(*args, **kwargs)
            refs = out if isinstance(out, list) else [out]
            return [self._track(r) for r in refs]

        return await asyncio.to_thread(do)

    async def _do_create_actor(self, cls, args_blob: bytes, opts: dict):
        args, kwargs = self._loads(args_blob)
        args = self._revive_handles(args)
        kwargs = self._revive_handles(kwargs)
        opts = self._xlate_opts(opts)
        ac = ray_tpu.remote(cls)
        if opts:
            ac = ac.options(**opts)

        def do():
            handle = ac.remote(*args, **kwargs)
            raw = handle._actor_id.binary()
            self._actors[raw] = handle
            return raw

        return await asyncio.to_thread(do)

    async def h_submit(self, fn_blob: bytes, args_blob: bytes, opts: dict):
        return await self._do_submit(self._fn(fn_blob), args_blob, opts)

    def _import_obj(self, module: str, qualname: str):
        """Resolve ``module`` + dotted ``qualname`` to a live object —
        the xlang function-descriptor path: non-Python drivers (cpp/
        include/ray_tpu/api.h PyTask/PyActor) name functions instead of
        shipping cloudpickle blobs (reference: cross-language function
        descriptors, SURVEY §2.5)."""
        import importlib

        obj = importlib.import_module(module)
        for part in qualname.split("."):
            obj = getattr(obj, part)
        return obj

    async def h_submit_named(self, module: str, name: str,
                             args_blob: bytes, opts: dict):
        return await self._do_submit(self._import_obj(module, name),
                                     args_blob, opts)

    async def h_create_named_actor(self, module: str, qualname: str,
                                   args_blob: bytes, opts: dict):
        return await self._do_create_actor(self._import_obj(module, qualname),
                                           args_blob, opts)

    async def h_create_actor(self, cls_blob: bytes, args_blob: bytes,
                             opts: dict):
        return await self._do_create_actor(self._fn(cls_blob), args_blob, opts)

    async def h_actor_call(self, actor_raw: bytes, method_name: str,
                           args_blob: bytes, num_returns: int):
        handle = self._actors.get(actor_raw)
        if handle is None:
            # an id learned xlang (e.g. returned from a Python task to the
            # C++ driver): serve it anyway
            from ray_tpu.common.ids import ActorID
            from ray_tpu.core_worker.actor import ActorHandle

            handle = self._actors[actor_raw] = ActorHandle(
                ActorID(actor_raw))
        args, kwargs = self._loads(args_blob)
        args = self._revive_handles(args)
        kwargs = self._revive_handles(kwargs)

        def do():
            out = getattr(handle, method_name).remote(*args, **kwargs)
            refs = out if isinstance(out, list) else [out]
            return [self._track(r) for r in refs]

        return await asyncio.to_thread(do)

    # ------------------------------------------------ placement groups
    async def h_create_placement_group(self, bundles, strategy: str,
                                       name=None):
        def do():
            pg = ray_tpu.placement_group(
                [dict(b) for b in bundles], strategy=strategy,
                name=name or None)
            raw = pg.id.binary()
            self._pgs[raw] = pg
            return raw

        return await asyncio.to_thread(do)

    async def h_placement_group_ready(self, pg_raw: bytes,
                                      timeout_s: float = 60.0):
        pg = self._pgs[pg_raw]
        return await asyncio.to_thread(lambda: pg.wait(timeout_s))

    async def h_remove_placement_group(self, pg_raw: bytes):
        pg = self._pgs.pop(pg_raw, None)
        if pg is not None:
            await asyncio.to_thread(ray_tpu.remove_placement_group, pg)
        return True

    async def h_cancel(self, raw_id: bytes, force: bool = False):
        ref = self._refs.get(raw_id)
        if ref is None:
            return {"status": "not_found"}
        return await asyncio.to_thread(
            lambda: ray_tpu.cancel(ref, force=force))

    async def h_kill_actor(self, actor_raw: bytes, no_restart: bool):
        handle = self._actors.get(actor_raw)
        if handle is None:
            return False
        await asyncio.to_thread(ray_tpu.kill, handle, no_restart=no_restart)
        return True

    async def h_get_named_actor(self, name: str, namespace: str):
        def do():
            try:
                handle = ray_tpu.get_actor(name, namespace or "default")
            except ValueError:
                return None
            raw = handle._actor_id.binary()
            self._actors[raw] = handle
            return raw

        return await asyncio.to_thread(do)

    async def h_release(self, raw_ids: List[bytes]):
        for r in raw_ids:
            self._refs.pop(r, None)
        return True

    async def h_cluster_resources(self):
        return await asyncio.to_thread(ray_tpu.cluster_resources)

    async def h_available_resources(self):
        return await asyncio.to_thread(ray_tpu.available_resources)

    async def h_nodes(self):
        nodes = await asyncio.to_thread(ray_tpu.nodes)
        for n in nodes:
            if isinstance(n.get("node_id"), bytes):
                n["node_id"] = n["node_id"].hex()
        return nodes

    # ---------------------------------------------------------------- main
    def run(self):
        import signal

        stop = threading.Event()
        # end_session SIGTERMs this process; the default handler would kill
        # it mid-sleep WITHOUT running the finally below, so the session's
        # job would never call finish_job and its actors would leak until
        # the GCS driver-health loop notices. Exit promptly and cleanly.
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        ray_tpu.init()  # RT_ADDRESS from the client server
        self.server.start()
        host, port = self.server.address
        print(f"SESSION_READY {host} {port}", flush=True)
        try:
            while not stop.wait(1.0):
                if time.monotonic() - self._last_heartbeat > \
                        HEARTBEAT_TIMEOUT_S:
                    break  # client gone: release the job and exit
        finally:
            ray_tpu.shutdown()


if __name__ == "__main__":
    SessionDriver().run()
