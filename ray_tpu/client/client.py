"""Thin client — the user-side half of ``ray://``.

Reference: ``python/ray/util/client/worker.py`` (client worker translating
the ray API onto the wire) + ``api.py`` (client-side handle types). One
connection to the head's client server; the full framework never loads on
the client — refs are opaque ids, values cross as pickled blobs.
"""

from __future__ import annotations

import io
import pickle
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import cloudpickle

from ray_tpu.rpc.rpc import RetryableRpcClient


class ClientObjectRef:
    __slots__ = ("_raw", "_ctx", "__weakref__")

    def __init__(self, raw: bytes, ctx: "ClientContext"):
        self._raw = raw
        self._ctx = ctx

    def hex(self) -> str:
        return self._raw.hex()

    def __repr__(self):
        return f"ClientObjectRef({self._raw.hex()[:16]})"

    def __eq__(self, other):
        return isinstance(other, ClientObjectRef) and self._raw == other._raw

    def __hash__(self):
        return hash(self._raw)

    def __del__(self):
        ctx = self._ctx
        if ctx is not None and not ctx._closed:
            ctx._queue_release(self._raw)


class _ClientPickler(cloudpickle.CloudPickler):
    def persistent_id(self, obj):
        if isinstance(obj, ClientObjectRef):
            return ("rt_ref", obj._raw)
        return None


class _ClientUnpickler(pickle.Unpickler):
    def __init__(self, f, ctx: "ClientContext"):
        super().__init__(f)
        self._ctx = ctx

    def persistent_load(self, pid):
        tag, raw = pid
        if tag != "rt_ref":
            raise pickle.UnpicklingError(f"unknown persistent id {tag!r}")
        return ClientObjectRef(raw, self._ctx)


class ClientContext:
    """One connected ``ray://`` session."""

    def __init__(self, host: str, port: int,
                 runtime_env: Optional[dict] = None):
        self._closed = False
        self._proxy = RetryableRpcClient((host, port))
        self.session_id = f"client-{uuid.uuid4().hex[:12]}"
        reply = self._proxy.call("new_session", session_id=self.session_id,
                                 runtime_env=runtime_env, timeout=120.0)
        if not reply.get("ok"):
            raise ConnectionError(
                f"client session failed: {reply.get('error')}")
        self._session = RetryableRpcClient(tuple(reply["address"]))
        self._release_buf: List[bytes] = []
        self._release_lock = threading.Lock()
        self._hb = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._hb.start()

    # ------------------------------------------------------------ plumbing
    def _dumps(self, value) -> bytes:
        buf = io.BytesIO()
        _ClientPickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(value)
        return buf.getvalue()

    def _loads(self, blob: bytes):
        return _ClientUnpickler(io.BytesIO(blob), self).load()

    def _queue_release(self, raw: bytes):
        with self._release_lock:
            self._release_buf.append(raw)

    def _heartbeat_loop(self):
        while not self._closed:
            time.sleep(5.0)
            if self._closed:
                return
            with self._release_lock:
                batch, self._release_buf = self._release_buf, []
            try:
                if batch:
                    self._session.call("release", raw_ids=batch)
                self._session.call("heartbeat")
            except Exception:  # noqa: BLE001 - reconnect handled by client
                pass

    # ------------------------------------------------------------- surface
    def put(self, value: Any) -> ClientObjectRef:
        raw = self._session.call("put", blob=self._dumps(value))
        return ClientObjectRef(raw, self)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        if single:
            refs = [refs]
        reply = self._session.call(
            "get", raw_ids=[r._raw for r in refs], timeout_s=timeout,
            timeout=(timeout + 10.0) if timeout else 600.0)
        if not reply["ok"]:
            raise self._loads(reply["error"])
        values = [self._loads(b) for b in reply["values"]]
        return values[0] if single else values

    def wait(self, refs: Sequence[ClientObjectRef], *, num_returns: int,
             timeout: Optional[float]):
        ready_raw = set(self._session.call(
            "wait", raw_ids=[r._raw for r in refs], num_returns=num_returns,
            timeout_s=timeout,
            timeout=(timeout + 10.0) if timeout else 600.0))
        ready = [r for r in refs if r._raw in ready_raw]
        not_ready = [r for r in refs if r._raw not in ready_raw]
        return ready, not_ready

    def submit(self, fn, args, kwargs, opts: dict) -> List[ClientObjectRef]:
        raws = self._session.call(
            "submit", fn_blob=cloudpickle.dumps(fn),
            args_blob=self._dumps((args, kwargs)), opts=opts, timeout=600.0)
        return [ClientObjectRef(r, self) for r in raws]

    def create_actor(self, cls, args, kwargs, opts: dict) -> "ClientActorHandle":
        raw = self._session.call(
            "create_actor", cls_blob=cloudpickle.dumps(cls),
            args_blob=self._dumps((args, kwargs)), opts=opts, timeout=600.0)
        methods = [m for m in dir(cls)
                   if not m.startswith("_") and callable(getattr(cls, m))]
        return ClientActorHandle(raw, self, methods)

    def actor_call(self, actor_raw: bytes, method: str, args, kwargs,
                   num_returns: int = 1) -> List[ClientObjectRef]:
        raws = self._session.call(
            "actor_call", actor_raw=actor_raw, method_name=method,
            args_blob=self._dumps((args, kwargs)), num_returns=num_returns,
            timeout=600.0)
        return [ClientObjectRef(r, self) for r in raws]

    def kill(self, handle: "ClientActorHandle", no_restart: bool = True):
        self._session.call("kill_actor", actor_raw=handle._raw,
                           no_restart=no_restart)

    def cancel(self, ref: ClientObjectRef, force: bool = False):
        return self._session.call("cancel", raw_id=ref._raw, force=force)

    def get_actor(self, name: str, namespace: str = "default"):
        raw = self._session.call("get_named_actor", name=name,
                                 namespace=namespace)
        if raw is None:
            raise ValueError(f"no alive actor named {name!r}")
        return ClientActorHandle(raw, self, [])

    def cluster_resources(self) -> Dict[str, float]:
        return self._session.call("cluster_resources")

    def available_resources(self) -> Dict[str, float]:
        return self._session.call("available_resources")

    def nodes(self) -> List[dict]:
        return self._session.call("nodes")

    def disconnect(self):
        if self._closed:
            return
        self._closed = True
        try:
            self._proxy.call("end_session", session_id=self.session_id,
                             timeout=10.0)
        except Exception:  # noqa: BLE001
            pass
        self._session.close()
        self._proxy.close()


class ClientRemoteFunction:
    def __init__(self, fn, ctx: ClientContext, opts: Optional[dict] = None):
        self._fn = fn
        self._ctx = ctx
        self._opts = opts or {}

    def remote(self, *args, **kwargs):
        num_returns = self._opts.get("num_returns", 1)
        refs = self._ctx.submit(self._fn, args, kwargs, self._opts)
        return refs[0] if num_returns == 1 else refs

    def options(self, **opts):
        merged = dict(self._opts)
        merged.update(opts)
        return ClientRemoteFunction(self._fn, self._ctx, merged)


class ClientActorClass:
    def __init__(self, cls, ctx: ClientContext, opts: Optional[dict] = None):
        self._cls = cls
        self._ctx = ctx
        self._opts = opts or {}

    def remote(self, *args, **kwargs) -> "ClientActorHandle":
        return self._ctx.create_actor(self._cls, args, kwargs, self._opts)

    def options(self, **opts):
        merged = dict(self._opts)
        merged.update(opts)
        return ClientActorClass(self._cls, self._ctx, merged)


class ClientActorHandle:
    def __init__(self, raw: bytes, ctx: ClientContext, methods: List[str]):
        self._raw = raw
        self._ctx = ctx
        self._methods = methods

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClientMethod(self, name)


class _ClientMethod:
    def __init__(self, handle: ClientActorHandle, name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs):
        refs = self._handle._ctx.actor_call(
            self._handle._raw, self._name, args, kwargs)
        return refs[0]
