"""Ray-Client equivalent: drive a cluster from OUTSIDE its network.

Reference: ``python/ray/util/client/`` (``ray://`` — a proxy server on the
head spawns one server-side driver per client session; the client speaks
one connection and never needs to be reachable from the cluster).

``ray_tpu.init(address="ray://host:port")`` enters client mode.
"""

from .client import ClientContext, ClientObjectRef
from .server import ClientServer

__all__ = ["ClientContext", "ClientObjectRef", "ClientServer"]
