"""Cluster resource view shared by the GCS and every raylet.

Equivalent of the reference's ClusterResourceScheduler's node map
(src/ray/raylet/scheduling/cluster_resource_scheduler.h:45 +
cluster_resource_data.h): a versioned {node_id: NodeResources} snapshot fed by
resource gossip.  The GCS holds the authoritative copy; raylets hold replicas
updated via the resource pubsub channel (the RaySyncer role,
src/ray/common/ray_syncer/ray_syncer.h:87).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional, Tuple

from ray_tpu.common.ids import NodeID
from ray_tpu.common.resources import NodeResources


@dataclass
class NodeEntry:
    node_id: NodeID
    address: Tuple[str, int]  # raylet RPC address
    resources: NodeResources
    seq: int = 0  # gossip version; stale updates are dropped
    alive: bool = True
    last_seen: float = field(default_factory=time.monotonic)
    object_store_address: Optional[str] = None  # shm store socket path (same-host)
    # node transfer-service endpoint (object_store/transfer.py): where
    # other nodes pull this node's sealed/spilled objects from
    transfer_address: Optional[Tuple[str, int]] = None


class ClusterView:
    """Thread-safe node table with versioned updates."""

    def __init__(self):
        self._nodes: Dict[NodeID, NodeEntry] = {}
        self._lock = threading.Lock()

    def upsert(self, entry: NodeEntry) -> bool:
        """Insert/refresh a node. Returns False if dropped as stale."""
        with self._lock:
            cur = self._nodes.get(entry.node_id)
            if cur is not None and cur.seq > entry.seq:
                return False
            self._nodes[entry.node_id] = entry
            return True

    def update_resources(self, node_id: NodeID, snapshot: dict, seq: int) -> bool:
        with self._lock:
            cur = self._nodes.get(node_id)
            if cur is None or seq <= cur.seq:
                return False
            cur.resources = NodeResources.from_snapshot(snapshot)
            cur.seq = seq
            cur.last_seen = time.monotonic()
            return True

    def mark_dead(self, node_id: NodeID) -> Optional[NodeEntry]:
        with self._lock:
            cur = self._nodes.get(node_id)
            if cur is not None and cur.alive:
                cur.alive = False
                return cur
            return None

    def remove(self, node_id: NodeID) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def get(self, node_id: NodeID) -> Optional[NodeEntry]:
        with self._lock:
            return self._nodes.get(node_id)

    def alive_nodes(self) -> Iterator[NodeEntry]:
        with self._lock:
            return iter([e for e in self._nodes.values() if e.alive])

    def all_nodes(self) -> Iterator[NodeEntry]:
        with self._lock:
            return iter(list(self._nodes.values()))

    def total_resources(self) -> dict:
        out: Dict[str, float] = {}
        for e in self.alive_nodes():
            for k, v in e.resources.total.to_dict().items():
                out[k] = out.get(k, 0) + v
        return out

    def available_resources(self) -> dict:
        out: Dict[str, float] = {}
        for e in self.alive_nodes():
            for k, v in e.resources.available.to_dict().items():
                out[k] = out.get(k, 0) + v
        return out
