"""Scheduling policies.

Node-selection strategies mirroring the reference's policy objects
(src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h:28-49,
bundle_scheduling_policy.h), plus a TPU-slice-aware gang policy that has no
reference counterpart: ICI topology makes TPU placement non-fungible, so
bundle policies can require all bundles land on nodes of one slice
(label ``rt.io/tpu-slice``).
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Dict, List, Optional, Sequence

from ray_tpu.common.config import GLOBAL_CONFIG
from ray_tpu.common.ids import NodeID
from ray_tpu.common.resources import LABEL_SLICE_NAME, NodeResources, ResourceRequest
from ray_tpu.common.task_spec import (
    DefaultStrategy,
    NodeAffinityStrategy,
    NodeLabelStrategy,
    SchedulingStrategy,
    SpreadStrategy,
)
from .cluster_state import ClusterView, NodeEntry


def _score(entry: NodeEntry, local_node: Optional[NodeID]) -> float:
    """Hybrid policy score: lower is better.  Prefers (1) low utilization up to
    a threshold — packing below it, spreading above — and (2) locality."""
    threshold = GLOBAL_CONFIG.get("scheduler_spread_threshold")
    util = entry.resources.utilization()
    score = 0.0 if util <= threshold else util
    if local_node is not None and entry.node_id == local_node:
        score -= 0.25  # locality bonus: prefer granting locally
    return score


def pick_node(
    view: ClusterView,
    request: ResourceRequest,
    strategy: Optional[SchedulingStrategy] = None,
    local_node: Optional[NodeID] = None,
    rng: Optional[random.Random] = None,
    require_available: bool = True,
    arg_bytes_by_node: Optional[Dict[str, int]] = None,
) -> Optional[NodeEntry]:
    """Select a node for one request.  Returns None if nothing is feasible
    (caller decides to queue or fail).

    ``arg_bytes_by_node`` ({node_id_hex: total argument bytes resident
    there}) is the data-locality hint (reference: the locality-aware lease
    policy, ``locality_aware_scheduling``): among usable candidates the
    node holding the most argument bytes wins outright — shipping the task
    is cheaper than shipping its args — with the hybrid pack/spread score
    only breaking ties.  Explicit placement strategies (affinity, labels,
    spread) are never overridden by the hint."""
    rng = rng or random
    strategy = strategy or DefaultStrategy()
    nodes = list(view.alive_nodes())

    if isinstance(strategy, NodeAffinityStrategy):
        entry = view.get(strategy.node_id)
        ok = (
            entry is not None
            and entry.alive
            and (entry.resources.is_available(request) if require_available
                 else entry.resources.is_feasible(request))
        )
        if ok:
            return entry
        if strategy.soft:
            return pick_node(view, request, DefaultStrategy(), local_node, rng, require_available)
        return None

    if isinstance(strategy, NodeLabelStrategy):
        from ray_tpu.common.resources import LabelSelector

        hard = LabelSelector(strategy.hard)
        nodes = [n for n in nodes if hard.matches(n.resources.labels)]
        if strategy.soft:
            soft = LabelSelector(strategy.soft)
            preferred = [n for n in nodes if soft.matches(n.resources.labels)]
            if preferred:
                nodes = preferred

    def usable(n: NodeEntry) -> bool:
        return n.resources.is_available(request) if require_available else n.resources.is_feasible(request)

    candidates = [n for n in nodes if usable(n)]
    if not candidates:
        return None

    if isinstance(strategy, SpreadStrategy):
        # round-robin-ish: least utilized first, random tiebreak
        return min(candidates, key=lambda n: (n.resources.utilization(), rng.random()))

    if arg_bytes_by_node and GLOBAL_CONFIG.get("locality_scheduling"):
        best = max(candidates,
                   key=lambda n: (arg_bytes_by_node.get(n.node_id.hex(), 0),
                                  -_score(n, local_node)))
        if arg_bytes_by_node.get(best.node_id.hex(), 0) > 0:
            return best

    # hybrid: score, then top-k random choice to avoid herding
    scored = sorted(candidates, key=lambda n: _score(n, local_node))
    k = max(
        GLOBAL_CONFIG.get("scheduler_top_k_absolute"),
        int(len(scored) * GLOBAL_CONFIG.get("scheduler_top_k_fraction")),
    )
    return rng.choice(scored[:k])


# ---------------------------------------------------------------------------
# Placement group bundle policies (gang scheduling)
# ---------------------------------------------------------------------------

class BundlePlacementError(Exception):
    pass


def place_bundles(
    view: ClusterView,
    bundles: Sequence[ResourceRequest],
    strategy: str,
    rng: Optional[random.Random] = None,
) -> Optional[List[NodeID]]:
    """Map each bundle to a node. Strategies: PACK, SPREAD, STRICT_PACK,
    STRICT_SPREAD, SLICE_PACK (all bundles on nodes sharing one TPU slice
    label, one bundle per node — the SPMD gang primitive).

    Returns None if currently infeasible (PGs stay pending), raises
    BundlePlacementError if *never* feasible.
    """
    rng = rng or random
    nodes = list(view.alive_nodes())
    if strategy == "SLICE_PACK":
        return _place_slice_pack(nodes, bundles, rng)

    # simulate allocations on copies so one node's capacity isn't double-counted
    sim: Dict[NodeID, NodeResources] = {
        n.node_id: NodeResources.from_snapshot(n.resources.snapshot()) for n in nodes
    }
    order = {n.node_id: n for n in nodes}

    def nodes_sorted_for(strategy_: str) -> List[NodeID]:
        if strategy_ in ("PACK", "STRICT_PACK"):
            return sorted(sim, key=lambda nid: sim[nid].utilization(), reverse=True)
        return sorted(sim, key=lambda nid: sim[nid].utilization())

    placement: List[NodeID] = []
    used_nodes: set = set()
    for bundle in bundles:
        placed = False
        for nid in nodes_sorted_for(strategy):
            if strategy == "STRICT_SPREAD" and nid in used_nodes:
                continue
            if not sim[nid].labels and order[nid].resources.labels:
                sim[nid].labels = dict(order[nid].resources.labels)
            if sim[nid].allocate(bundle) is not None:
                placement.append(nid)
                used_nodes.add(nid)
                placed = True
                break
        if not placed:
            if strategy == "STRICT_PACK" and placement:
                # STRICT_PACK: everything must fit one node; retry all-on-one
                return _place_strict_pack(nodes, bundles)
            return None
    if strategy == "STRICT_PACK" and len(set(placement)) > 1:
        return _place_strict_pack(nodes, bundles)
    return placement


def _place_strict_pack(nodes: List[NodeEntry], bundles: Sequence[ResourceRequest]):
    for n in nodes:
        sim = NodeResources.from_snapshot(n.resources.snapshot())
        if all(sim.allocate(b) is not None for b in bundles):
            return [n.node_id] * len(bundles)
    return None


def _place_slice_pack(nodes: List[NodeEntry], bundles: Sequence[ResourceRequest], rng):
    """All bundles on one ICI slice, spread across its member nodes."""
    by_slice: Dict[str, List[NodeEntry]] = defaultdict(list)
    for n in nodes:
        slice_name = n.resources.labels.get(LABEL_SLICE_NAME)
        if slice_name:
            by_slice[slice_name].append(n)
    for slice_name in sorted(by_slice, key=lambda s: len(by_slice[s])):
        members = by_slice[slice_name]
        if len(members) < len(bundles):
            continue
        sim = {n.node_id: NodeResources.from_snapshot(n.resources.snapshot()) for n in members}
        placement: List[NodeID] = []
        used: set = set()
        ok = True
        for bundle in bundles:
            for nid in sim:
                if nid in used:
                    continue
                if sim[nid].allocate(bundle) is not None:
                    placement.append(nid)
                    used.add(nid)
                    break
            else:
                ok = False
                break
        if ok:
            return placement
    return None
