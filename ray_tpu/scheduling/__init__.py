from .cluster_state import ClusterView, NodeEntry  # noqa: F401
from . import policies  # noqa: F401
