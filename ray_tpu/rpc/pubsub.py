"""Long-poll pub/sub (reference: src/ray/pubsub/publisher.h, subscriber.h).

The publisher keeps a bounded mailbox per subscriber; subscribers long-poll
(`pubsub_poll`) and receive message batches.  Used for object location
updates, actor state changes, node events, and log streams — anywhere the
control plane pushes state to many listeners without a persistent stream.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.common import faults

from .rpc import IoContext, RetryableRpcClient, RpcError, RpcServer

_MAILBOX_CAP = 10_000


class Publisher:
    """Server-side half. Attach to an RpcServer with :meth:`attach`."""

    def __init__(self):
        # subscriber_id -> channel -> set of keys (empty set = all keys)
        self._subs: Dict[str, Dict[str, set]] = defaultdict(dict)
        self._mail: Dict[str, List[tuple]] = defaultdict(list)
        self._wakeups: Dict[str, asyncio.Event] = {}
        self._lock = threading.Lock()
        # wakeup coalescing: a burst of publishes (batched actor ALIVEs,
        # resource gossip) schedules ONE loop callback that fires every
        # pending subscriber event, instead of one call_soon_threadsafe
        # (pipe write + loop iteration) per message per subscriber
        self._pending_wakeups: set = set()
        self._wakeup_scheduled = False

    def attach(self, server: RpcServer, prefix: str = "pubsub_"):
        server.register(prefix + "subscribe", self._handle_subscribe)
        server.register(prefix + "unsubscribe", self._handle_unsubscribe)
        server.register(prefix + "poll", self._handle_poll)

    async def _handle_subscribe(self, subscriber_id: str, channel: str, key: Optional[str] = None):
        with self._lock:
            keys = self._subs[subscriber_id].setdefault(channel, set())
            if key is not None:
                keys.add(key)
        return True

    async def _handle_unsubscribe(self, subscriber_id: str, channel: Optional[str] = None):
        with self._lock:
            if channel is None:
                self._subs.pop(subscriber_id, None)
                self._mail.pop(subscriber_id, None)
                self._wakeups.pop(subscriber_id, None)
            else:
                self._subs.get(subscriber_id, {}).pop(channel, None)
        return True

    async def _handle_poll(self, subscriber_id: str, timeout: float = 30.0):
        with self._lock:
            known = subscriber_id in self._subs
        if not known:
            # Publisher restarted (GCS failover) and lost the subscription
            # table; the poller must re-issue its subscribes before messages
            # can flow again.
            return "__resubscribe__"
        event = self._wakeups.setdefault(subscriber_id, asyncio.Event())
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                batch = self._mail.pop(subscriber_id, [])
            if batch:
                return batch
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return []
            event.clear()
            try:
                await asyncio.wait_for(event.wait(), remaining)
            except asyncio.TimeoutError:
                return []

    def publish(self, channel: str, key: str, message: Any):
        """Thread-safe; deliver to all subscribers matching (channel, key)."""
        try:
            faults.fault_point("pubsub.publish")
        except faults.FaultInjected:
            # a lost control-plane event, not a raised one: publishers
            # are fire-and-forget, so the fault manifests as listeners
            # simply never hearing this message (they must converge via
            # polling / later events, never hang on one publish)
            return
        with self._lock:
            targets = []
            for sub_id, channels in self._subs.items():
                keys = channels.get(channel)
                if keys is None:
                    continue
                if keys and key not in keys:
                    continue
                box = self._mail[sub_id]
                if len(box) < _MAILBOX_CAP:
                    box.append((channel, key, message))
                targets.append(sub_id)
            if not targets:
                return
            self._pending_wakeups.update(targets)
            if self._wakeup_scheduled:
                return  # a flush is already on its way: ride it
            self._wakeup_scheduled = True
        IoContext.current().loop.call_soon_threadsafe(self._flush_wakeups)

    def _flush_wakeups(self):
        with self._lock:
            targets = self._pending_wakeups
            self._pending_wakeups = set()
            self._wakeup_scheduled = False
        for sub_id in targets:
            ev = self._wakeups.get(sub_id)
            if ev is not None:
                ev.set()


class Subscriber:
    """Client-side half: background long-poll loop dispatching to callbacks."""

    def __init__(self, subscriber_id: str, address: Tuple[str, int], prefix: str = "pubsub_"):
        self.subscriber_id = subscriber_id
        self._prefix = prefix
        self._client = RetryableRpcClient(address)
        self._callbacks: Dict[str, Callable[[str, Any], None]] = {}
        self._keys: Dict[str, Optional[str]] = {}
        self._stopped = threading.Event()
        self._task = None
        self._io = IoContext.current()
        # Callbacks run on a dedicated thread (ordered), never on the shared IO
        # loop — a blocking callback must not stall every RPC in the process.
        import queue as _queue

        self._dispatch_q: "_queue.Queue" = _queue.Queue()
        self._dispatcher = threading.Thread(target=self._dispatch_loop, daemon=True)
        self._dispatcher.start()

    def _dispatch_loop(self):
        while True:
            item = self._dispatch_q.get()
            if item is None:
                return
            cb, key, message = item
            try:
                cb(key, message)
            except Exception:  # noqa: BLE001 - subscriber callbacks must not kill the loop
                import logging

                logging.getLogger(__name__).exception("pubsub callback failed")

    def subscribe(self, channel: str, callback: Callable[[str, Any], None], key: Optional[str] = None):
        self._callbacks[channel] = callback
        self._keys[channel] = key
        self._client.call(self._prefix + "subscribe", subscriber_id=self.subscriber_id, channel=channel, key=key)
        if self._task is None:
            self._task = True
            self._io.spawn_threadsafe(self._poll_loop())

    async def _poll_loop(self):
        from ray_tpu.common.retry import RetryPolicy

        backoff = RetryPolicy(base_s=0.2, cap_s=1.0)  # unbounded attempts:
        failures = 0  # a subscriber must outlive any publisher outage
        while not self._stopped.is_set():
            try:
                batch = await self._client.call_async(
                    self._prefix + "poll", subscriber_id=self.subscriber_id, timeout=35.0
                )
                failures = 0
            except Exception:  # noqa: BLE001 - keep polling through transient failures
                if self._stopped.is_set():
                    return
                failures += 1
                await backoff.asleep(failures)
                continue
            if batch == "__resubscribe__":
                # publisher restarted: replay every subscription, then poll
                for channel in list(self._callbacks):
                    try:
                        await self._client.call_async(
                            self._prefix + "subscribe",
                            subscriber_id=self.subscriber_id,
                            channel=channel, key=self._keys.get(channel))
                    except Exception:  # noqa: BLE001
                        break
                await asyncio.sleep(0.05)
                continue
            for channel, key, message in batch or []:
                cb = self._callbacks.get(channel)
                if cb is not None:
                    self._dispatch_q.put((cb, key, message))

    def close(self):
        self._stopped.set()
        self._dispatch_q.put(None)
        self._client.close()
