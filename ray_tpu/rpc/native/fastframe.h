/* fastframe.h — the wire layer shared by fastloop.c and fastspec.c.
 *
 * Pure C (no Python.h): the frame codec and the robust fd writer live
 * here so `scripts/run_tsan.sh` can compile them into a sanitizer
 * harness (cpp/test/tsan_fastloop.cc) without an embedded interpreter.
 * Everything is little-endian on the wire — the pure-Python fallback
 * decoder (struct "<QII"/"<I") must read what this code writes on any
 * host.
 *
 * Frame format (both directions of the fastloop channel):
 *   [u32 payload_len][u64 req_id][payload bytes]
 */
#ifndef RT_FASTFRAME_H
#define RT_FASTFRAME_H

#include <errno.h>
#include <poll.h>
#include <stdint.h>
#include <stddef.h>
#include <string.h>
#include <sys/uio.h>

#define FF_HDR_SIZE 12u
#define FF_MAX_FRAME (1u << 30) /* 1 GiB sanity cap */

static inline void ff_put_u32(unsigned char *p, uint32_t v) {
    p[0] = v & 0xff; p[1] = (v >> 8) & 0xff;
    p[2] = (v >> 16) & 0xff; p[3] = (v >> 24) & 0xff;
}
static inline uint32_t ff_get_u32(const unsigned char *p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
           ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}
static inline void ff_put_u64(unsigned char *p, uint64_t v) {
    ff_put_u32(p, (uint32_t)(v & 0xffffffffu));
    ff_put_u32(p + 4, (uint32_t)(v >> 32));
}
static inline uint64_t ff_get_u64(const unsigned char *p) {
    return (uint64_t)ff_get_u32(p) | ((uint64_t)ff_get_u32(p + 4) << 32);
}

/* Parse the next complete frame at *off.  Returns 1 and advances *off
 * past the frame when one is complete, 0 when more bytes are needed,
 * -1 on a corrupt length prefix (connection must drop). */
static inline int ff_next_frame(const unsigned char *buf, size_t len,
                                size_t *off, uint64_t *req_id,
                                const unsigned char **payload,
                                uint32_t *plen) {
    if (len - *off < FF_HDR_SIZE) return 0;
    uint32_t n = ff_get_u32(buf + *off);
    if (n > FF_MAX_FRAME) return -1;
    if (len - *off < FF_HDR_SIZE + (size_t)n) return 0;
    *req_id = ff_get_u64(buf + *off + 4);
    *payload = buf + *off + FF_HDR_SIZE;
    *plen = n;
    *off += FF_HDR_SIZE + n;
    return 1;
}

/* Robust write of a full frame on a (possibly non-blocking) fd; the
 * caller must serialize concurrent writers on the same fd (fastloop
 * holds the connection's write mutex) and must NOT hold the GIL. */
static inline int ff_write_frame_fd(int fd, uint64_t req_id,
                                    const char *payload, size_t len) {
    unsigned char hdr[FF_HDR_SIZE];
    ff_put_u32(hdr, (uint32_t)len);
    ff_put_u64(hdr + 4, req_id);
    struct iovec iov[2] = {
        {.iov_base = hdr, .iov_len = FF_HDR_SIZE},
        {.iov_base = (void *)payload, .iov_len = len},
    };
    size_t total = FF_HDR_SIZE + len, sent = 0;
    while (sent < total) {
        ssize_t n = writev(fd, iov, iov[1].iov_len ? 2 : 1);
        if (n < 0) {
            if (errno == EINTR) continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                struct pollfd p = {.fd = fd, .events = POLLOUT};
                if (poll(&p, 1, 30000) <= 0) return -1;
                continue;
            }
            return -1;
        }
        sent += (size_t)n;
        size_t left = (size_t)n;
        if (iov[0].iov_len) {
            size_t take = left < iov[0].iov_len ? left : iov[0].iov_len;
            iov[0].iov_base = (char *)iov[0].iov_base + take;
            iov[0].iov_len -= take;
            left -= take;
        }
        iov[1].iov_base = (char *)iov[1].iov_base + left;
        iov[1].iov_len -= left;
    }
    return 0;
}

/* ------------------------------------------------------------------ */
/* fastspec v2 (normal-task) record codec — pure C, shared by          */
/* fastspec.c (the CPython extension) and cpp/test/tsan_fastframe.cc   */
/* (the sanitizer harness), so concurrent record parse is TSAN/ASAN    */
/* covered without an embedded interpreter.  Wire form (fastspec.c     */
/* header comment):                                                    */
/*   magic "RTFS" | ver u8=2 | num_returns u32 | port u32 |            */
/*   8 x (len u32 | bytes): task_id, job_id, caller_worker_id, host,   */
/*                          qualname, serialized_func, args_payload,   */
/*                          display_name                               */
/* ------------------------------------------------------------------ */

#define FF_SPEC_MAGIC "RTFS"
#define FF_SPEC_TASK_VERSION 2u
#define FF_TASK_NBLOBS 8u
#define FF_TASK_HDR (4u + 1u + 4u + 4u)

typedef struct {
    const unsigned char *ptr;
    uint32_t len;
} ff_span;

typedef struct {
    uint32_t num_returns;
    uint32_t port;
    ff_span blobs[FF_TASK_NBLOBS];
} ff_task_record;

/* Packed byte size of a v2 record (callers allocate; this layer never
 * does — fastframe.h stays allocation-free by contract, enforced by the
 * native-race-audit analysis pass). */
static inline size_t ff_task_size(const ff_task_record *rec) {
    size_t total = FF_TASK_HDR;
    for (unsigned i = 0; i < FF_TASK_NBLOBS; i++)
        total += 4 + (size_t)rec->blobs[i].len;
    return total;
}

/* Serialize into `out` (at least ff_task_size(rec) bytes); returns the
 * number of bytes written. */
static inline size_t ff_task_write(const ff_task_record *rec,
                                   unsigned char *out) {
    unsigned char *p = out;
    memcpy(p, FF_SPEC_MAGIC, 4); p += 4;
    *p++ = (unsigned char)FF_SPEC_TASK_VERSION;
    ff_put_u32(p, rec->num_returns); p += 4;
    ff_put_u32(p, rec->port); p += 4;
    for (unsigned i = 0; i < FF_TASK_NBLOBS; i++) {
        ff_put_u32(p, rec->blobs[i].len); p += 4;
        if (rec->blobs[i].len) {
            memcpy(p, rec->blobs[i].ptr, rec->blobs[i].len);
            p += rec->blobs[i].len;
        }
    }
    return (size_t)(p - out);
}

/* Parse a v2 record.  Blob spans alias `buf` (zero-copy; the caller
 * keeps buf alive).  Returns 0 on success, -1 when buf is not a v2
 * record, -2 when truncated/corrupt. */
static inline int ff_task_parse(const unsigned char *buf, size_t len,
                                ff_task_record *rec) {
    if (len < FF_TASK_HDR || memcmp(buf, FF_SPEC_MAGIC, 4) != 0)
        return -1;
    if (buf[4] != FF_SPEC_TASK_VERSION)
        return -1;
    const unsigned char *p = buf + 5;
    const unsigned char *end = buf + len;
    rec->num_returns = ff_get_u32(p); p += 4;
    rec->port = ff_get_u32(p); p += 4;
    for (unsigned i = 0; i < FF_TASK_NBLOBS; i++) {
        if ((size_t)(end - p) < 4) return -2;
        uint32_t n = ff_get_u32(p); p += 4;
        if ((size_t)(end - p) < (size_t)n) return -2;
        rec->blobs[i].ptr = p;
        rec->blobs[i].len = n;
        p += n;
    }
    return 0;
}

#endif /* RT_FASTFRAME_H */
