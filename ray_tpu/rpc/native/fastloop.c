/* fastloop.c — C dispatch loop for the actor-call and normal-task hot
 * paths.
 *
 * SURVEY §2.5 native-core mandate: the reference's per-call path is C++
 * end-to-end (src/ray/core_worker/transport/normal_task_submitter.cc
 * PushNormalTask, src/ray/rpc/grpc_server.h); ours was asyncio Python,
 * and profiling put ~230 µs/call in asyncio scheduling + coroutine
 * resumption alone (PERF_PLAN.md round-4 appendix).  This extension
 * removes that floor for eligible actor calls:
 *
 *   Server — one C thread per worker: poll() accept/read loop, frames
 *     dispatched straight into a Python handler while holding the GIL
 *     (the handler is the worker's fast-execute entry; for
 *     deferred/threaded execution it returns None and later calls
 *     send_reply() from any thread).
 *   Client — blocking writes from the caller's own thread (no event
 *     loop hop) + one C reader thread completing replies via a Python
 *     callback.
 *
 * Wire format per frame, both directions:
 *   [u32 payload_len][u64 req_id][payload bytes]
 * req_id is the actor-call sequence number; the reply carries the same
 * id.  Transport failures surface as on_reply(0, None) client-side and
 * as connection teardown server-side — both sides then fall back to the
 * ordinary asyncio RPC path, whose seq-dedup replay protocol makes the
 * switchover exactly-once.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <pthread.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

/* Wire codec + robust writer live in fastframe.h (pure C, no Python)
 * so run_tsan.sh can drive them under the sanitizers. */
#include "fastframe.h"

#define HDR_SIZE FF_HDR_SIZE
#define MAX_FRAME FF_MAX_FRAME
#define write_frame_fd ff_write_frame_fd
#define get_u32 ff_get_u32
#define get_u64 ff_get_u64

/* ------------------------------------------------------------------ */
/* Server                                                             */
/* ------------------------------------------------------------------ */

typedef struct Conn {
    uint64_t id;
    int fd;
    int dead;
    int refs; /* registry + transient send_reply holders */
    pthread_mutex_t wmutex;
    unsigned char *buf;
    size_t cap, len;
    struct Conn *next;
} Conn;

typedef struct {
    PyObject_HEAD
    int listen_fd;
    int port;
    int stop_pipe[2];
    pthread_t thread;
    int running;
    PyObject *handler;
    pthread_mutex_t reg_mutex;
    Conn *conns;
    uint64_t next_conn_id;
} ServerObject;

static void conn_decref(Conn *c) {
    /* caller holds reg_mutex */
    if (--c->refs == 0) {
        close(c->fd);
        pthread_mutex_destroy(&c->wmutex);
        free(c->buf);
        free(c);
    }
}

static void server_drop_conn(ServerObject *self, Conn *c) {
    pthread_mutex_lock(&self->reg_mutex);
    if (!c->dead) {
        c->dead = 1;
        Conn **pp = &self->conns;
        while (*pp && *pp != c) pp = &(*pp)->next;
        if (*pp) *pp = c->next;
        shutdown(c->fd, SHUT_RDWR);
        conn_decref(c);
    }
    pthread_mutex_unlock(&self->reg_mutex);
}

/* Dispatch every complete frame in c->buf.  Runs on the server thread
 * without the GIL held on entry. */
static int server_dispatch(ServerObject *self, Conn *c) {
    size_t off = 0;
    int rc = 0;
    for (;;) {
        uint64_t req_id;
        const unsigned char *payload;
        uint32_t plen;
        int fr = ff_next_frame(c->buf, c->len, &off, &req_id, &payload,
                               &plen);
        if (fr <= 0) { if (fr < 0) rc = -1; break; }
        PyGILState_STATE g = PyGILState_Ensure();
        PyObject *res = PyObject_CallFunction(
            self->handler, "KKy#", (unsigned long long)c->id,
            (unsigned long long)req_id,
            (const char *)payload, (Py_ssize_t)plen);
        if (res == NULL) {
            /* Handler bug: the Python side wraps user errors into reply
             * payloads, so an escape here is unexpected.  Surface it and
             * kill the connection — the caller's resend protocol takes
             * the slow path from there. */
            PyErr_WriteUnraisable(self->handler);
            PyGILState_Release(g);
            rc = -1;
            break;
        }
        if (res == Py_None) {
            /* reply deferred: Python will call send_reply() later */
            Py_DECREF(res);
            PyGILState_Release(g);
        } else {
            char *pbuf;
            Py_ssize_t pn;
            if (PyBytes_AsStringAndSize(res, &pbuf, &pn) < 0) {
                PyErr_WriteUnraisable(self->handler);
                Py_DECREF(res);
                PyGILState_Release(g);
                rc = -1;
                break;
            }
            /* write with the GIL released; wmutex orders us against any
             * concurrent send_reply() for deferred frames */
            Py_BEGIN_ALLOW_THREADS
            pthread_mutex_lock(&c->wmutex);
            rc = write_frame_fd(c->fd, req_id, pbuf, (size_t)pn);
            pthread_mutex_unlock(&c->wmutex);
            Py_END_ALLOW_THREADS
            Py_DECREF(res);
            PyGILState_Release(g);
            if (rc < 0) break;
        }
    }
    if (off > 0) {
        memmove(c->buf, c->buf + off, c->len - off);
        c->len -= off;
    }
    return rc;
}

static void *server_main(void *arg) {
    ServerObject *self = (ServerObject *)arg;
    for (;;) {
        /* snapshot conns under the registry lock */
        pthread_mutex_lock(&self->reg_mutex);
        size_t nconn = 0;
        for (Conn *c = self->conns; c; c = c->next) nconn++;
        struct pollfd *pfds = malloc((nconn + 2) * sizeof(*pfds));
        Conn **order = malloc((nconn + 1) * sizeof(*order));
        if (!pfds || !order) {
            pthread_mutex_unlock(&self->reg_mutex);
            free(pfds); free(order);
            return NULL;
        }
        pfds[0].fd = self->stop_pipe[0];
        pfds[0].events = POLLIN;
        pfds[1].fd = self->listen_fd;
        pfds[1].events = POLLIN;
        size_t i = 0;
        for (Conn *c = self->conns; c; c = c->next, i++) {
            c->refs++; /* held across the poll */
            order[i] = c;
            pfds[i + 2].fd = c->fd;
            pfds[i + 2].events = POLLIN;
        }
        pthread_mutex_unlock(&self->reg_mutex);

        int pr = poll(pfds, nconn + 2, 1000);
        int stopping = 0;
        if (pr > 0) {
            if (pfds[0].revents) stopping = 1;
            if (!stopping && (pfds[1].revents & POLLIN)) {
                int fd = accept(self->listen_fd, NULL, NULL);
                if (fd >= 0) {
                    int one = 1;
                    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one,
                               sizeof(one));
                    Conn *c = calloc(1, sizeof(Conn));
                    if (c) {
                        c->fd = fd;
                        c->refs = 1;
                        pthread_mutex_init(&c->wmutex, NULL);
                        pthread_mutex_lock(&self->reg_mutex);
                        c->id = ++self->next_conn_id;
                        c->next = self->conns;
                        self->conns = c;
                        pthread_mutex_unlock(&self->reg_mutex);
                    } else {
                        close(fd);
                    }
                }
            }
            for (i = 0; !stopping && i < nconn; i++) {
                Conn *c = order[i];
                short rev = pfds[i + 2].revents;
                if (!rev || c->dead) continue;
                if (rev & POLLIN) {
                    if (c->cap - c->len < 65536) {
                        size_t ncap = c->cap ? c->cap * 2 : 131072;
                        while (ncap - c->len < 65536) ncap *= 2;
                        unsigned char *nb = realloc(c->buf, ncap);
                        if (!nb) { server_drop_conn(self, c); continue; }
                        c->buf = nb;
                        c->cap = ncap;
                    }
                    ssize_t n = recv(c->fd, c->buf + c->len,
                                     c->cap - c->len, 0);
                    if (n <= 0) {
                        if (n < 0 && (errno == EINTR || errno == EAGAIN))
                            continue;
                        server_drop_conn(self, c);
                        continue;
                    }
                    c->len += (size_t)n;
                    if (server_dispatch(self, c) < 0)
                        server_drop_conn(self, c);
                } else if (rev & (POLLHUP | POLLERR | POLLNVAL)) {
                    server_drop_conn(self, c);
                }
            }
        }
        /* release the poll refs */
        pthread_mutex_lock(&self->reg_mutex);
        for (i = 0; i < nconn; i++) conn_decref(order[i]);
        pthread_mutex_unlock(&self->reg_mutex);
        free(pfds);
        free(order);
        if (stopping || pr < 0) break;
    }
    return NULL;
}

static PyObject *Server_start(ServerObject *self, PyObject *noargs) {
    (void)noargs;
    if (self->running) Py_RETURN_NONE;
    if (pthread_create(&self->thread, NULL, server_main, self) != 0)
        return PyErr_SetFromErrno(PyExc_OSError);
    self->running = 1;
    Py_RETURN_NONE;
}

static PyObject *Server_stop(ServerObject *self, PyObject *noargs) {
    (void)noargs;
    if (self->running) {
        ssize_t r = write(self->stop_pipe[1], "x", 1);
        (void)r;
        Py_BEGIN_ALLOW_THREADS
        pthread_join(self->thread, NULL);
        Py_END_ALLOW_THREADS
        self->running = 0;
        pthread_mutex_lock(&self->reg_mutex);
        while (self->conns) {
            Conn *c = self->conns;
            self->conns = c->next;
            c->dead = 1;
            shutdown(c->fd, SHUT_RDWR);
            conn_decref(c);
        }
        pthread_mutex_unlock(&self->reg_mutex);
    }
    Py_RETURN_NONE;
}

static PyObject *Server_send_reply(ServerObject *self, PyObject *args) {
    unsigned long long conn_id, req_id;
    Py_buffer payload;
    if (!PyArg_ParseTuple(args, "KKy*", &conn_id, &req_id, &payload))
        return NULL;
    pthread_mutex_lock(&self->reg_mutex);
    Conn *c = self->conns;
    while (c && c->id != conn_id) c = c->next;
    if (c) c->refs++;
    pthread_mutex_unlock(&self->reg_mutex);
    if (!c) {
        PyBuffer_Release(&payload);
        Py_RETURN_FALSE; /* peer gone: its resend protocol recovers */
    }
    int rc;
    Py_BEGIN_ALLOW_THREADS
    pthread_mutex_lock(&c->wmutex);
    rc = write_frame_fd(c->fd, (uint64_t)req_id, payload.buf, payload.len);
    pthread_mutex_unlock(&c->wmutex);
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&payload);
    pthread_mutex_lock(&self->reg_mutex);
    conn_decref(c);
    pthread_mutex_unlock(&self->reg_mutex);
    if (rc < 0) Py_RETURN_FALSE;
    Py_RETURN_TRUE;
}

static PyObject *Server_get_port(ServerObject *self, void *closure) {
    (void)closure;
    return PyLong_FromLong(self->port);
}

static int Server_init(ServerObject *self, PyObject *args, PyObject *kw) {
    static char *kwlist[] = {"handler", "host", NULL};
    PyObject *handler;
    const char *host = "0.0.0.0";
    if (!PyArg_ParseTupleAndKeywords(args, kw, "O|s", kwlist, &handler,
                                     &host))
        return -1;
    if (!PyCallable_Check(handler)) {
        PyErr_SetString(PyExc_TypeError, "handler must be callable");
        return -1;
    }
    Py_INCREF(handler);
    self->handler = handler;
    self->listen_fd = -1;
    self->stop_pipe[0] = self->stop_pipe[1] = -1;
    self->running = 0;
    self->conns = NULL;
    self->next_conn_id = 0;
    pthread_mutex_init(&self->reg_mutex, NULL);

    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) goto oserr;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_port = 0;
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1)
        addr.sin_addr.s_addr = htonl(INADDR_ANY);
    if (bind(fd, (struct sockaddr *)&addr, sizeof(addr)) < 0 ||
        listen(fd, 128) < 0) {
        close(fd);
        goto oserr;
    }
    socklen_t alen = sizeof(addr);
    getsockname(fd, (struct sockaddr *)&addr, &alen);
    self->port = ntohs(addr.sin_port);
    self->listen_fd = fd;
    if (pipe(self->stop_pipe) < 0) {
        close(fd);
        self->listen_fd = -1;
        goto oserr;
    }
    return 0;
oserr:
    PyErr_SetFromErrno(PyExc_OSError);
    return -1;
}

static void Server_dealloc(ServerObject *self) {
    if (self->running) {
        PyObject *r = Server_stop(self, NULL);
        Py_XDECREF(r);
    }
    if (self->listen_fd >= 0) close(self->listen_fd);
    if (self->stop_pipe[0] >= 0) close(self->stop_pipe[0]);
    if (self->stop_pipe[1] >= 0) close(self->stop_pipe[1]);
    pthread_mutex_destroy(&self->reg_mutex);
    Py_XDECREF(self->handler);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyMethodDef Server_methods[] = {
    {"start", (PyCFunction)Server_start, METH_NOARGS, NULL},
    {"stop", (PyCFunction)Server_stop, METH_NOARGS, NULL},
    {"send_reply", (PyCFunction)Server_send_reply, METH_VARARGS,
     "send_reply(conn_id, req_id, payload) -> bool"},
    {NULL, NULL, 0, NULL},
};

static PyGetSetDef Server_getset[] = {
    {"port", (getter)Server_get_port, NULL, NULL, NULL},
    {NULL, NULL, NULL, NULL, NULL},
};

static PyTypeObject ServerType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "_fastloop.Server",
    .tp_basicsize = sizeof(ServerObject),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = PyType_GenericNew,
    .tp_init = (initproc)Server_init,
    .tp_dealloc = (destructor)Server_dealloc,
    .tp_methods = Server_methods,
    .tp_getset = Server_getset,
};

/* ------------------------------------------------------------------ */
/* Client                                                             */
/* ------------------------------------------------------------------ */

typedef struct {
    PyObject_HEAD
    int fd;
    int running;
    int closed;
    pthread_t thread;
    pthread_mutex_t wmutex;
    PyObject *on_reply;
} ClientObject;

static void *client_main(void *arg) {
    ClientObject *self = (ClientObject *)arg;
    unsigned char *buf = NULL;
    size_t cap = 0, len = 0;
    for (;;) {
        if (cap - len < 65536) {
            size_t ncap = cap ? cap * 2 : 131072;
            while (ncap - len < 65536) ncap *= 2;
            unsigned char *nb = realloc(buf, ncap);
            if (!nb) break;
            buf = nb;
            cap = ncap;
        }
        ssize_t n = recv(self->fd, buf + len, cap - len, 0);
        if (n <= 0) {
            if (n < 0 && errno == EINTR) continue;
            break;
        }
        len += (size_t)n;
        size_t off = 0;
        int bad = 0;
        for (;;) {
            uint64_t req_id;
            const unsigned char *payload;
            uint32_t plen;
            int fr = ff_next_frame(buf, len, &off, &req_id, &payload,
                                   &plen);
            if (fr <= 0) { bad = (fr < 0); break; }
            PyGILState_STATE g = PyGILState_Ensure();
            PyObject *r = PyObject_CallFunction(
                self->on_reply, "Ky#", (unsigned long long)req_id,
                (const char *)payload, (Py_ssize_t)plen);
            if (r == NULL)
                PyErr_WriteUnraisable(self->on_reply);
            Py_XDECREF(r);
            PyGILState_Release(g);
        }
        if (bad) break;
        if (off > 0) {
            memmove(buf, buf + off, len - off);
            len -= off;
        }
    }
    free(buf);
    /* connection over: tell Python unless close() was requested (then the
     * owner already knows and the interpreter may be tearing down) */
    if (!self->closed) {
        PyGILState_STATE g = PyGILState_Ensure();
        PyObject *r =
            PyObject_CallFunction(self->on_reply, "KO", 0ULL, Py_None);
        if (r == NULL) PyErr_WriteUnraisable(self->on_reply);
        Py_XDECREF(r);
        PyGILState_Release(g);
    }
    return NULL;
}

static int Client_init(ClientObject *self, PyObject *args, PyObject *kw) {
    static char *kwlist[] = {"host", "port", "on_reply", "timeout", NULL};
    const char *host;
    int port;
    PyObject *on_reply;
    double timeout = 10.0;
    if (!PyArg_ParseTupleAndKeywords(args, kw, "siO|d", kwlist, &host,
                                     &port, &on_reply, &timeout))
        return -1;
    if (!PyCallable_Check(on_reply)) {
        PyErr_SetString(PyExc_TypeError, "on_reply must be callable");
        return -1;
    }
    self->fd = -1;
    self->running = 0;
    self->closed = 0;
    pthread_mutex_init(&self->wmutex, NULL);

    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) {
        PyErr_SetFromErrno(PyExc_OSError);
        return -1;
    }
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
        close(fd);
        PyErr_SetString(PyExc_OSError, "fastloop client needs an IPv4 "
                                       "address, not a hostname");
        return -1;
    }
    /* honour the timeout: non-blocking connect + poll, then back to
     * blocking mode (a raw connect() can hang ~2 min on a blackholed
     * port, and callers may be on an event loop) */
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int rc;
    Py_BEGIN_ALLOW_THREADS
    rc = connect(fd, (struct sockaddr *)&addr, sizeof(addr));
    if (rc < 0 && errno == EINPROGRESS) {
        struct pollfd p = {.fd = fd, .events = POLLOUT};
        int pr = poll(&p, 1, (int)(timeout * 1000.0));
        if (pr == 1) {
            int soerr = 0;
            socklen_t slen = sizeof(soerr);
            getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
            if (soerr == 0) {
                rc = 0;
            } else {
                errno = soerr;
                rc = -1;
            }
        } else {
            errno = ETIMEDOUT;
            rc = -1;
        }
    }
    Py_END_ALLOW_THREADS
    if (rc < 0) {
        close(fd);
        PyErr_SetFromErrno(PyExc_ConnectionError);
        return -1;
    }
    fcntl(fd, F_SETFL, flags);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    self->fd = fd;
    Py_INCREF(on_reply);
    self->on_reply = on_reply;
    if (pthread_create(&self->thread, NULL, client_main, self) != 0) {
        close(fd);
        self->fd = -1;
        PyErr_SetFromErrno(PyExc_OSError);
        return -1;
    }
    self->running = 1;
    return 0;
}

static PyObject *Client_call(ClientObject *self, PyObject *args) {
    unsigned long long req_id;
    Py_buffer payload;
    if (!PyArg_ParseTuple(args, "Ky*", &req_id, &payload)) return NULL;
    if (self->fd < 0 || self->closed) {
        PyBuffer_Release(&payload);
        PyErr_SetString(PyExc_ConnectionError, "fastloop client closed");
        return NULL;
    }
    int rc;
    Py_BEGIN_ALLOW_THREADS
    pthread_mutex_lock(&self->wmutex);
    rc = write_frame_fd(self->fd, (uint64_t)req_id, payload.buf,
                        payload.len);
    pthread_mutex_unlock(&self->wmutex);
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&payload);
    if (rc < 0) {
        PyErr_SetString(PyExc_ConnectionError, "fastloop write failed");
        return NULL;
    }
    Py_RETURN_NONE;
}

static PyObject *Client_close(ClientObject *self, PyObject *noargs) {
    (void)noargs;
    self->closed = 1;
    if (self->fd >= 0) shutdown(self->fd, SHUT_RDWR);
    if (self->running) {
        Py_BEGIN_ALLOW_THREADS
        pthread_join(self->thread, NULL);
        Py_END_ALLOW_THREADS
        self->running = 0;
    }
    if (self->fd >= 0) {
        close(self->fd);
        self->fd = -1;
    }
    Py_RETURN_NONE;
}

static void Client_dealloc(ClientObject *self) {
    PyObject *r = Client_close(self, NULL);
    Py_XDECREF(r);
    pthread_mutex_destroy(&self->wmutex);
    Py_XDECREF(self->on_reply);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyMethodDef Client_methods[] = {
    {"call", (PyCFunction)Client_call, METH_VARARGS,
     "call(req_id, payload) — write one frame; replies arrive via "
     "on_reply(req_id, payload) on the reader thread"},
    {"close", (PyCFunction)Client_close, METH_NOARGS, NULL},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject ClientType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "_fastloop.Client",
    .tp_basicsize = sizeof(ClientObject),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = PyType_GenericNew,
    .tp_init = (initproc)Client_init,
    .tp_dealloc = (destructor)Client_dealloc,
    .tp_methods = Client_methods,
};

static struct PyModuleDef fastloop_module = {
    PyModuleDef_HEAD_INIT, "_fastloop",
    "C dispatch loop for actor-call push/reply (see fastloop.c header)",
    -1, NULL, NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__fastloop(void) {
    if (PyType_Ready(&ServerType) < 0 || PyType_Ready(&ClientType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&fastloop_module);
    if (!m) return NULL;
    Py_INCREF(&ServerType);
    PyModule_AddObject(m, "Server", (PyObject *)&ServerType);
    Py_INCREF(&ClientType);
    PyModule_AddObject(m, "Client", (PyObject *)&ClientType);
    return m;
}
