/* fastspec — native codec for the per-call submit records.
 *
 * Reference obligation (SURVEY §2.5): the reference's per-call submit
 * path is C++ end-to-end (core_worker/transport/actor_task_submitter.cc +
 * normal_task_submitter.cc + protobuf TaskSpec); a pickled 20-field
 * Python dataclass graph per call is the single biggest per-call CPU
 * cost in this runtime's equivalent. This module packs/unpacks the
 * submit record in one buffer.
 *
 * v1 — actor call (pack/unpack):
 *   magic "RTFS" | ver u8=1 |
 *   seq u64 | num_returns u32 | port u32 |
 *   7 x (len u32 | bytes):   task_id, job_id, actor_id, caller_worker_id,
 *                            host, method, args_payload
 *
 * v2 — normal task (pack_task/unpack_task), the lease-cached dispatch
 * channel's record:
 *   magic "RTFS" | ver u8=2 |
 *   num_returns u32 | port u32 |
 *   8 x (len u32 | bytes):   task_id, job_id, caller_worker_id, host,
 *                            qualname, serialized_func, args_payload,
 *                            display_name
 *
 * The args payload is ONE pickle of the plain (args, kwargs) made by the
 * caller; everything else is fixed metadata. CPython C API only (no
 * pybind11 in this image); compiled on first import like shm_store.cc.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <endian.h>
#include <stdint.h>
#include <string.h>

#include "fastframe.h" /* shared little-endian helpers (pure C) */

static const char MAGIC[4] = {'R', 'T', 'F', 'S'};
static const uint8_t VERSION = 1;
static const uint8_t TASK_VERSION = 2;
#define N_BLOBS 7
#define N_TASK_BLOBS 8

/* Wire integers are little-endian: the pure-Python fallback decoder
 * (struct "<QII"/"<I") must read what this codec writes on any host. */
static void put_u32(char **p, uint32_t v) { ff_put_u32((unsigned char *)*p, v); *p += 4; }
static void put_u64(char **p, uint64_t v) { ff_put_u64((unsigned char *)*p, v); *p += 8; }
static uint32_t get_u32(const char **p) { uint32_t v = ff_get_u32((const unsigned char *)*p); *p += 4; return v; }
static uint64_t get_u64(const char **p) { uint64_t v = ff_get_u64((const unsigned char *)*p); *p += 8; return v; }

static PyObject *
fastspec_pack(PyObject *self, PyObject *args)
{
    Py_buffer blobs[N_BLOBS];
    unsigned long long seq;
    unsigned int num_returns;
    unsigned int port;
    /* task_id job_id actor_id caller_wid host method payload seq nret port */
    if (!PyArg_ParseTuple(args, "y*y*y*y*y*y*y*KII",
                          &blobs[0], &blobs[1], &blobs[2], &blobs[3],
                          &blobs[4], &blobs[5], &blobs[6],
                          &seq, &num_returns, &port)) {
        return NULL;
    }
    Py_ssize_t total = 4 + 1 + 8 + 4 + 4;
    for (int i = 0; i < N_BLOBS; i++) {
        if ((uint64_t)blobs[i].len > UINT32_MAX) {
            for (int j = 0; j < N_BLOBS; j++) PyBuffer_Release(&blobs[j]);
            PyErr_SetString(PyExc_OverflowError,
                            "fastspec blob exceeds u32 length prefix");
            return NULL;
        }
        total += 4 + blobs[i].len;
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, total);
    if (out == NULL) {
        for (int i = 0; i < N_BLOBS; i++) PyBuffer_Release(&blobs[i]);
        return NULL;
    }
    char *p = PyBytes_AS_STRING(out);
    memcpy(p, MAGIC, 4); p += 4;
    *p++ = (char)VERSION;
    put_u64(&p, (uint64_t)seq);
    put_u32(&p, (uint32_t)num_returns);
    put_u32(&p, (uint32_t)port);
    for (int i = 0; i < N_BLOBS; i++) {
        put_u32(&p, (uint32_t)blobs[i].len);
        memcpy(p, blobs[i].buf, blobs[i].len); p += blobs[i].len;
        PyBuffer_Release(&blobs[i]);
    }
    return out;
}

static PyObject *
fastspec_unpack(PyObject *self, PyObject *args)
{
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) {
        return NULL;
    }
    const char *p = (const char *)buf.buf;
    const char *end = p + buf.len;
    if (buf.len < 4 + 1 + 8 + 4 + 4 || memcmp(p, MAGIC, 4) != 0) {
        PyBuffer_Release(&buf);
        PyErr_SetString(PyExc_ValueError, "not a fastspec buffer");
        return NULL;
    }
    p += 4;
    uint8_t ver = (uint8_t)*p++;
    if (ver != VERSION) {
        PyBuffer_Release(&buf);
        PyErr_Format(PyExc_ValueError, "fastspec version %d unsupported", ver);
        return NULL;
    }
    uint64_t seq = get_u64(&p);
    uint32_t num_returns = get_u32(&p);
    uint32_t port = get_u32(&p);

    PyObject *tuple = PyTuple_New(N_BLOBS + 3);
    if (tuple == NULL) {
        PyBuffer_Release(&buf);
        return NULL;
    }
    for (int i = 0; i < N_BLOBS; i++) {
        if (p + 4 > end) goto corrupt;
        uint32_t len = get_u32(&p);
        if ((Py_ssize_t)len > end - p) goto corrupt;
        PyObject *b = PyBytes_FromStringAndSize(p, (Py_ssize_t)len);
        if (b == NULL) {
            Py_DECREF(tuple);
            PyBuffer_Release(&buf);
            return NULL;
        }
        PyTuple_SET_ITEM(tuple, i, b);
        p += len;
    }
    PyTuple_SET_ITEM(tuple, N_BLOBS, PyLong_FromUnsignedLongLong(seq));
    PyTuple_SET_ITEM(tuple, N_BLOBS + 1, PyLong_FromUnsignedLong(num_returns));
    PyTuple_SET_ITEM(tuple, N_BLOBS + 2, PyLong_FromUnsignedLong(port));
    PyBuffer_Release(&buf);
    return tuple;

corrupt:
    Py_DECREF(tuple);
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "truncated fastspec buffer");
    return NULL;
}

/* v2 pack/unpack ride the pure-C record codec in fastframe.h
 * (ff_task_write/ff_task_parse) — the same functions the sanitizer
 * harness (cpp/test/tsan_fastframe.cc) drives under TSAN/ASAN with
 * concurrent writers, so the production parse path IS the audited
 * path. */

static PyObject *
fastspec_pack_task(PyObject *self, PyObject *args)
{
    Py_buffer blobs[N_TASK_BLOBS];
    unsigned int num_returns;
    unsigned int port;
    /* task_id job_id caller_wid host qualname func payload name
     * num_returns port */
    if (!PyArg_ParseTuple(args, "y*y*y*y*y*y*y*y*II",
                          &blobs[0], &blobs[1], &blobs[2], &blobs[3],
                          &blobs[4], &blobs[5], &blobs[6], &blobs[7],
                          &num_returns, &port)) {
        return NULL;
    }
    ff_task_record rec;
    rec.num_returns = (uint32_t)num_returns;
    rec.port = (uint32_t)port;
    for (int i = 0; i < N_TASK_BLOBS; i++) {
        if ((uint64_t)blobs[i].len > UINT32_MAX) {
            for (int j = 0; j < N_TASK_BLOBS; j++)
                PyBuffer_Release(&blobs[j]);
            PyErr_SetString(PyExc_OverflowError,
                            "fastspec blob exceeds u32 length prefix");
            return NULL;
        }
        rec.blobs[i].ptr = (const unsigned char *)blobs[i].buf;
        rec.blobs[i].len = (uint32_t)blobs[i].len;
    }
    PyObject *out =
        PyBytes_FromStringAndSize(NULL, (Py_ssize_t)ff_task_size(&rec));
    if (out == NULL) {
        for (int i = 0; i < N_TASK_BLOBS; i++) PyBuffer_Release(&blobs[i]);
        return NULL;
    }
    ff_task_write(&rec, (unsigned char *)PyBytes_AS_STRING(out));
    for (int i = 0; i < N_TASK_BLOBS; i++) PyBuffer_Release(&blobs[i]);
    return out;
}

static PyObject *
fastspec_unpack_task(PyObject *self, PyObject *args)
{
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf)) {
        return NULL;
    }
    ff_task_record rec;
    int rc = ff_task_parse((const unsigned char *)buf.buf,
                           (size_t)buf.len, &rec);
    if (rc != 0) {
        const unsigned char *b = (const unsigned char *)buf.buf;
        if (rc == -1 && buf.len >= 5 && memcmp(b, MAGIC, 4) == 0 &&
            b[4] != FF_SPEC_TASK_VERSION) {
            PyErr_Format(PyExc_ValueError,
                         "fastspec task version %d unsupported", b[4]);
        } else if (rc == -1 && (buf.len < 4 ||
                                memcmp(b, MAGIC, 4) != 0)) {
            PyErr_SetString(PyExc_ValueError, "not a fastspec buffer");
        } else {
            /* magic + supported version but short/corrupt body (parse
             * returned -1 for len < header or -2 mid-blob) */
            PyErr_SetString(PyExc_ValueError,
                            "truncated fastspec buffer");
        }
        PyBuffer_Release(&buf);
        return NULL;
    }
    PyObject *tuple = PyTuple_New(N_TASK_BLOBS + 2);
    if (tuple == NULL) {
        PyBuffer_Release(&buf);
        return NULL;
    }
    for (int i = 0; i < N_TASK_BLOBS; i++) {
        PyObject *b = PyBytes_FromStringAndSize(
            (const char *)rec.blobs[i].ptr, (Py_ssize_t)rec.blobs[i].len);
        if (b == NULL) {
            Py_DECREF(tuple);
            PyBuffer_Release(&buf);
            return NULL;
        }
        PyTuple_SET_ITEM(tuple, i, b);
    }
    PyTuple_SET_ITEM(tuple, N_TASK_BLOBS,
                     PyLong_FromUnsignedLong(rec.num_returns));
    PyTuple_SET_ITEM(tuple, N_TASK_BLOBS + 1,
                     PyLong_FromUnsignedLong(rec.port));
    PyBuffer_Release(&buf);
    return tuple;
}

static PyMethodDef FastspecMethods[] = {
    {"pack", fastspec_pack, METH_VARARGS,
     "pack(task_id, job_id, actor_id, caller_wid, host, method, payload, "
     "seq, num_returns, port) -> bytes"},
    {"unpack", fastspec_unpack, METH_VARARGS,
     "unpack(buf) -> (task_id, job_id, actor_id, caller_wid, host, method, "
     "payload, seq, num_returns, port)"},
    {"pack_task", fastspec_pack_task, METH_VARARGS,
     "pack_task(task_id, job_id, caller_wid, host, qualname, func, payload, "
     "name, num_returns, port) -> bytes"},
    {"unpack_task", fastspec_unpack_task, METH_VARARGS,
     "unpack_task(buf) -> (task_id, job_id, caller_wid, host, qualname, "
     "func, payload, name, num_returns, port)"},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef fastspecmodule = {
    PyModuleDef_HEAD_INIT, "_fastspec",
    "native actor-call submit record codec", -1, FastspecMethods
};

PyMODINIT_FUNC
PyInit__fastspec(void)
{
    return PyModule_Create(&fastspecmodule);
}
