"""Native (C) codecs for RPC hot paths — built on first import.

The CPython extension is compiled with the system toolchain against this
interpreter's headers (no pybind11 / pip in this image), same build-on-
demand pattern as ``object_store/native/shm_store.cc``.
"""

from __future__ import annotations

import importlib.util
import os
import struct
import subprocess
import sysconfig
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_fastspec.so")
_SRC = os.path.join(_DIR, "fastspec.c")
_HDR = os.path.join(_DIR, "fastframe.h")  # shared wire layer (both .so's)
_lock = threading.Lock()
_mod = None
_FAILED = object()  # build attempted and lost — don't re-run gcc per call


def _src_mtime(src: str) -> float:
    """Staleness anchor for a native source: the newest of the .c file and
    the shared fastframe.h it includes — editing the header alone must
    trigger a rebuild or tests measure the wrong code."""
    m = os.path.getmtime(src)
    try:
        m = max(m, os.path.getmtime(_HDR))
    except OSError:
        pass
    return m


def load_fastspec():
    """Returns the _fastspec extension module (building it if stale), or
    None when no compiler is available (pure-pickle fallback). A failed
    build is cached: the hot path must not re-spawn gcc per call."""
    global _mod
    if _mod is not None:
        return None if _mod is _FAILED else _mod
    with _lock:
        if _mod is not None:
            return None if _mod is _FAILED else _mod
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < _src_mtime(_SRC)):
                include = sysconfig.get_paths()["include"]
                tmp = _SO + f".tmp.{os.getpid()}"
                subprocess.run(
                    ["gcc", "-O2", "-fPIC", "-shared", f"-I{include}",
                     "-o", tmp, _SRC],
                    check=True, capture_output=True)
                os.replace(tmp, _SO)
            spec = importlib.util.spec_from_file_location("_fastspec", _SO)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _mod = mod
        except Exception:  # noqa: BLE001 - no compiler / arch mismatch
            _mod = _FAILED
        return None if _mod is _FAILED else _mod


_FL_SO = os.path.join(_DIR, "_fastloop.so")
_FL_SRC = os.path.join(_DIR, "fastloop.c")
_fl_lock = threading.Lock()
_fl_mod = None


def load_fastloop():
    """Returns the _fastloop extension (C dispatch loop for the actor-call
    hot path — see fastloop.c), or None when it can't be built; a failed
    build is cached so callers fall back to the asyncio path for good."""
    global _fl_mod
    if _fl_mod is not None:
        return None if _fl_mod is _FAILED else _fl_mod
    with _fl_lock:
        if _fl_mod is not None:
            return None if _fl_mod is _FAILED else _fl_mod
        try:
            if (not os.path.exists(_FL_SO)
                    or os.path.getmtime(_FL_SO) < _src_mtime(_FL_SRC)):
                include = sysconfig.get_paths()["include"]
                tmp = _FL_SO + f".tmp.{os.getpid()}"
                subprocess.run(
                    ["gcc", "-O2", "-fPIC", "-shared", "-pthread",
                     f"-I{include}", "-o", tmp, _FL_SRC],
                    check=True, capture_output=True)
                os.replace(tmp, _FL_SO)
            spec = importlib.util.spec_from_file_location("_fastloop",
                                                          _FL_SO)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _fl_mod = mod
        except Exception:  # noqa: BLE001 - no compiler / arch mismatch
            _fl_mod = _FAILED
        return None if _fl_mod is _FAILED else _fl_mod


def _read_blobs(blob: bytes, off: int, n: int):
    blobs = []
    for _ in range(n):
        if off + 4 > len(blob):
            raise ValueError("truncated fastspec buffer")
        (ln,) = struct.unpack_from("<I", blob, off)
        off += 4
        if off + ln > len(blob):
            raise ValueError("truncated fastspec buffer")
        blobs.append(blob[off:off + ln])
        off += ln
    return blobs


def unpack_fastspec(blob: bytes):
    """Decode a v1 (actor-call) fastspec buffer with the C codec when
    available, else a pure-Python reader — a receiver without a compiler
    must still accept fast-path pushes from nodes that have one."""
    mod = load_fastspec()
    if mod is not None:
        return mod.unpack(blob)
    if len(blob) < 21 or blob[:4] != b"RTFS" or blob[4] != 1:
        raise ValueError("not a fastspec v1 buffer")
    seq, num_returns, port = struct.unpack_from("<QII", blob, 5)
    blobs = _read_blobs(blob, 21, 7)
    return (*blobs, seq, num_returns, port)


def unpack_fasttask(blob: bytes):
    """Decode a v2 (normal-task) fastspec buffer, C codec or pure-Python
    fallback (same compiler-less receiver contract as unpack_fastspec)."""
    mod = load_fastspec()
    if mod is not None:
        return mod.unpack_task(blob)
    if len(blob) < 13 or blob[:4] != b"RTFS" or blob[4] != 2:
        raise ValueError("not a fastspec v2 buffer")
    num_returns, port = struct.unpack_from("<II", blob, 5)
    blobs = _read_blobs(blob, 13, 8)
    return (*blobs, num_returns, port)
