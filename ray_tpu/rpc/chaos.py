"""RPC fault injection.

Equivalent of the reference's rpc chaos hooks (src/ray/rpc/rpc_chaos.cc:30-49,
flag RAY_testing_rpc_failure in ray_config_def.h:845): a config string of the
form ``"Method1=0.2,Method2=0.05"`` makes the named RPC methods fail with the
given probability, on either the request or the response side.  Deterministic
under ``testing_rpc_failure_seed``.  This exists so every layer above RPC can
be chaos-tested from day one.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, Optional, Tuple

from ray_tpu.common.config import GLOBAL_CONFIG
from ray_tpu.common.status import RtConnectionError


class RpcChaosError(RtConnectionError):
    """Injected failure, distinguishable from real network errors in tests."""


class _ChaosState:
    def __init__(self):
        self._lock = threading.Lock()
        self._parsed_from: Optional[str] = None
        self._probs: Dict[str, float] = {}
        self._rng = random.Random()

    def _refresh(self):
        spec = GLOBAL_CONFIG.get("testing_rpc_failure")
        if spec == self._parsed_from:
            return
        probs: Dict[str, float] = {}
        for part in filter(None, (p.strip() for p in spec.split(","))):
            method, _, prob = part.partition("=")
            probs[method.strip()] = float(prob or 1.0)
        self._probs = probs
        self._parsed_from = spec
        seed = GLOBAL_CONFIG.get("testing_rpc_failure_seed")
        if seed:
            self._rng = random.Random(seed)

    def roll(self, method: str) -> Tuple[bool, bool]:
        """Returns (fail_request, fail_response)."""
        with self._lock:
            self._refresh()
            if not self._probs:
                return False, False
            p = self._probs.get(method, self._probs.get("*", 0.0))
            if p <= 0.0:
                return False, False
            if self._rng.random() < p:
                # Reference fails request vs response with equal chance: a
                # request-side failure means the server never saw it, a
                # response-side failure means it executed but the caller
                # doesn't know — exercising both idempotency paths.
                return (True, False) if self._rng.random() < 0.5 else (False, True)
            return False, False


_STATE = _ChaosState()


def maybe_inject_failure(method: str) -> Tuple[bool, bool]:
    return _STATE.roll(method)


def reset():
    global _STATE
    _STATE = _ChaosState()
