"""Typed wire schemas for core RPC methods.

Reference: ``src/ray/protobuf/*.proto`` — the reference's wire contract is
compiled IDL; ours is framed pickle envelopes, which round 2 shipped with
an *implicit* contract (SURVEY §1 row 0). This module makes the contract
explicit and machine-checked: each core method declares a
:class:`Message` of typed fields, the server validates inbound requests
against it (strict-by-default via ``rpc_schema_validation``), and the
table doubles as the protocol's documentation and versioning anchor.

Design notes vs protobuf:
- Values still travel as framed pickle (zero-copy buffer support,
  ``serialization.py``); the schema governs STRUCTURE, not encoding —
  the same split the reference has between protoc codegen and gRPC bytes.
- Unknown fields are allowed by default (wire compatibility for rolling
  upgrades: new clients may send fields old servers ignore), required
  fields and type mismatches are errors.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple, Type, Union

SCHEMA_VERSION = 1


class SchemaError(TypeError):
    pass


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    # a type, tuple of types, or None for "any pickled value"
    type: Union[Type, Tuple[Type, ...], None]
    required: bool = True
    # protocol version (rpc/protocol.py) that introduced the field: a
    # required field is only ENFORCED against peers new enough to know it
    # — the rolling-upgrade contract protobuf gets from field numbers
    since: int = 1

    def check(self, method: str, kwargs: Dict[str, Any],
              peer_protocol: int = 1_000_000) -> None:
        if self.name not in kwargs:
            if self.required and peer_protocol >= self.since:
                raise SchemaError(
                    f"{method}: missing required field {self.name!r}")
            return
        if self.type is None:
            return
        v = kwargs[self.name]
        if v is None and not self.required:
            return  # optional fields are nullable
        if not isinstance(v, self.type):
            raise SchemaError(
                f"{method}: field {self.name!r} expects "
                f"{self.type}, got {type(v).__name__}")


@dataclasses.dataclass(frozen=True)
class Message:
    name: str
    fields: Tuple[Field, ...]
    allow_unknown: bool = True

    def validate(self, kwargs: Dict[str, Any],
                 peer_protocol: int = 1_000_000) -> Dict[str, Any]:
        """Check and return the kwargs to dispatch. Unknown fields are
        STRIPPED (not just tolerated) when allowed: handlers don't take
        **kwargs, so passing a newer client's extra fields through would
        crash the handler and void the rolling-upgrade guarantee.
        ``peer_protocol`` relaxes required fields newer than the peer
        (``Field.since``)."""
        for f in self.fields:
            f.check(self.name, kwargs, peer_protocol)
        known = {f.name for f in self.fields}
        unknown = set(kwargs) - known
        if not unknown:
            return kwargs
        if not self.allow_unknown:
            raise SchemaError(
                f"{self.name}: unknown fields {sorted(unknown)}")
        return {k: v for k, v in kwargs.items() if k in known}


def _m(name: str, *fields: Field) -> Message:
    return Message(name, tuple(fields))


def req(name: str, type_=None) -> Field:
    return Field(name, type_, required=True)


def opt(name: str, type_=None) -> Field:
    return Field(name, type_, required=False)


_num = (int, float)

# The wire contract of the core services. One entry per RPC method;
# handlers without an entry skip validation (library-level RPCs whose
# payloads are full pickled objects).
RPC_SCHEMAS: Dict[str, Message] = {
    # ---- worker service (reference core_worker.proto) ----
    "push_task": _m("push_task", req("spec", bytes)),
    "cancel_task": _m("cancel_task", opt("object_id", bytes),
                      opt("task_id", bytes), opt("force", bool)),
    "cancel_running_task": _m("cancel_running_task", req("task_id", bytes),
                              opt("force", bool)),
    "create_actor": _m("create_actor", req("creation_spec", bytes),
                       req("node_id", bytes),
                       # coalesced device grant: chips ride the creation
                       # push instead of a separate set_visible_devices
                       # round trip (raylet h_start_actor)
                       opt("tpu_chips", (tuple, list))),
    "get_object": _m("get_object", req("object_id", bytes),
                     opt("timeout", _num)),
    "object_info": _m("object_info", req("object_id", bytes),
                      opt("timeout", _num)),
    "get_object_chunk": _m("get_object_chunk", req("object_id", bytes),
                           req("offset", int), req("length", int)),
    "free_object": _m("free_object", req("object_id", bytes),
                      opt("borrowed", bool), opt("worker_id", bytes)),
    "reconstruct_object": _m("reconstruct_object", req("object_id", bytes)),
    "report_generator_item": _m(
        "report_generator_item", req("task_id", bytes), opt("index", int),
        opt("done", bool), opt("total", int), opt("value", bytes),
        opt("error", bytes), opt("location", (tuple, list))),
    "incref_inflight": _m("incref_inflight", req("object_id", bytes),
                          opt("worker_id", bytes), opt("token", bytes)),
    "borrow_ack": _m("borrow_ack", req("object_id", bytes),
                     opt("worker_id", bytes), opt("token", bytes)),
    "borrow_release": _m("borrow_release", req("object_id", bytes),
                         opt("worker_id", bytes), opt("token", bytes)),
    # ---- raylet service (reference node_manager.proto) ----
    # NOTE: declare only fields the handler accepts — unknown inbound
    # fields are stripped pre-dispatch, so a field listed here but absent
    # from the handler would pass through and crash it.
    "request_worker_lease": _m(
        "request_worker_lease", req("lease_id", bytes),
        req("resources", dict), opt("strategy", bytes),
        opt("pg", (tuple, list)), opt("runtime_env", dict),
        opt("grant_only_local", bool), opt("job_id", bytes),
        # argument-locality hint: {node_id_hex: total_arg_bytes} from the
        # submitter's owner-side location cache (scheduling/policies.py)
        opt("locality", dict)),
    # coalesced grants: up to N same-shape leases in one round trip
    "request_worker_leases": _m(
        "request_worker_leases", req("lease_ids", list),
        req("resources", dict), opt("runtime_env", dict),
        opt("job_id", bytes)),
    "return_worker": _m("return_worker", req("lease_id", bytes),
                        opt("disconnect", bool)),
    "register_worker": _m("register_worker", req("worker_id", bytes),
                          req("address", (tuple, list)),
                          opt("fast_port", int)),
    "configure_worker": _m("configure_worker", opt("env_vars", dict),
                           opt("cwd", str)),
    "start_actor": _m("start_actor", req("creation_spec", bytes)),
    "kill_worker": _m("kill_worker", req("worker_id", bytes)),
    "worker_alive": _m("worker_alive", req("worker_id", bytes)),
    # ---- GCS service (reference gcs_service.proto) ----
    "register_node": _m("register_node", req("node_id", bytes),
                        req("address", (tuple, list)),
                        req("resources", dict), req("labels", dict),
                        opt("object_store_address", str),
                        # node transfer-service endpoint [host, port]
                        # (object_store/transfer.py)
                        opt("transfer_address", (tuple, list)),
                        opt("live_actors", list), opt("held_bundles", list)),
    "register_actor": _m("register_actor", req("creation_spec", bytes),
                         req("actor_id", bytes), req("job_id", bytes),
                         opt("name", str), opt("namespace", str),
                         opt("max_restarts", int)),
    # coalesced unnamed-actor registration (one RPC per driver-side burst)
    "register_actors": _m("register_actors", req("specs", list),
                          req("job_id", bytes)),
    "report_resources": _m("report_resources", req("node_id", bytes),
                           req("snapshot", dict), req("seq", int),
                           opt("pending", list), opt("stats", dict),
                           # leadership-fencing relay (gcs/failover.py)
                           Field("leader_epoch", int, required=False,
                                 since=2)),
    "report_actor_state": _m("report_actor_state", req("actor_id", bytes),
                             req("state", str), opt("worker_id", bytes),
                             opt("address", (tuple, list)),
                             opt("node_id", bytes), opt("death_cause", str),
                             opt("fast_port", int)),
    # object location directory (reference gcs_service.proto
    # ObjectLocationInfo): owner-coalesced batches of add/remove/spill
    # transitions, and bulk resolution for cold fetches
    "object_locations_update": _m("object_locations_update",
                                  req("updates", list)),
    "get_object_locations": _m("get_object_locations",
                               req("object_ids", list)),
    "kv_put": _m("kv_put", req("namespace", str), req("key", (bytes, str)),
                 req("value", bytes), opt("overwrite", bool)),
    "kv_get": _m("kv_get", req("namespace", str), req("key", (bytes, str))),
    "kv_del": _m("kv_del", req("namespace", str), req("key", (bytes, str))),
    "publish_worker_log": _m("publish_worker_log", req("job_id", str),
                             req("pid", int), req("worker_id", str),
                             req("stream", str), req("lines", list),
                             opt("actor_name", str)),
}


def validate(method: str, kwargs: Dict[str, Any],
             peer_protocol: int = 1_000_000) -> Dict[str, Any]:
    """Check a request against the wire contract and return the kwargs to
    dispatch (unknown fields stripped); pass-through for methods without
    a declared schema. ``peer_protocol`` is the connection-negotiated
    version of the requesting peer (rpc/protocol.py)."""
    schema = RPC_SCHEMAS.get(method)
    if schema is None:
        return kwargs
    return schema.validate(kwargs, peer_protocol)
