from .rpc import RpcClient, RpcError, RpcServer, RetryableRpcClient  # noqa: F401
from .chaos import maybe_inject_failure, RpcChaosError  # noqa: F401
