"""Wire-protocol versioning (reference: the 37 pinned proto files under
``src/ray/protobuf/`` — protobuf gives the reference field-number-stable
evolution; our framed-pickle envelopes get the equivalent guarantees from
an explicit protocol version, a connection handshake, and per-field
``since`` annotations in :mod:`ray_tpu.rpc.schema`).

Wire format (one TCP connection, many concurrent requests):

    frame   := header payload
    header  := <u32 little-endian payload length> <u8 frame type>
    payload := pickled dict

Frame types:

    1  REQ    {"id": int, "method": str, "kwargs": dict, "v": int}
    2  RESP   {"id": int, "result": ...} | {"id", "error": (kind, c, tb)}
    3  HELLO  client->server {"protocol": int, "min_protocol": int,
                              "schema": int}
              server->client {"protocol": int, "min_protocol": int,
                              "schema": int} |
                             {"error": str}  (then the server closes)

Negotiation: the client's FIRST frame on a connection is HELLO; the
server answers with its own versions and both sides speak
``min(client.protocol, server.protocol)`` from then on. A peer whose
protocol falls below the other's ``min_protocol`` is rejected loudly at
connect time instead of failing obscurely mid-call. A connection whose
first frame is a REQ (no HELLO) is served as protocol 1 — the rolling-
upgrade path for peers predating the handshake.

Version history (append-only; never renumber):

    1  round 2-3 implicit contract: REQ/RESP framed pickle, no "v" stamp
    2  round 4: HELLO handshake, "v" stamp on REQ, Field.since gating

Native codecs version independently: fastspec TaskSpec blobs are
self-describing via their ``RTFS`` magic (rpc/native/fastspec.c); a
layout change must introduce a new magic, not mutate the old one.
"""

from __future__ import annotations

# The version this build SPEAKS.
PROTOCOL_VERSION = 2
# The oldest peer this build still accepts (raise to drop legacy paths).
MIN_SUPPORTED_PROTOCOL = 1


class ProtocolError(Exception):
    """Version negotiation failed (incompatible peer)."""


def negotiate(peer_protocol: int, peer_min: int) -> int:
    """Return the protocol version to speak with a peer, or raise.

    Symmetric: both sides run this on the other's (protocol, min) and
    arrive at the same answer."""
    if peer_protocol < MIN_SUPPORTED_PROTOCOL:
        raise ProtocolError(
            f"peer speaks protocol {peer_protocol}, below this build's "
            f"minimum {MIN_SUPPORTED_PROTOCOL}")
    if PROTOCOL_VERSION < peer_min:
        raise ProtocolError(
            f"this build speaks protocol {PROTOCOL_VERSION}, below the "
            f"peer's minimum {peer_min}")
    return min(PROTOCOL_VERSION, peer_protocol)
