"""Asyncio RPC layer: framed-message server + multiplexed retryable client.

Fills the role of the reference's gRPC wrappers (src/ray/rpc/grpc_server.h,
grpc_client.h, retryable_grpc_client.h).  Design notes:

- Transport is a length-prefixed pickle envelope over TCP.  We deliberately do
  not use gRPC: the control plane is low-rate, the data plane goes through the
  shared-memory object store, and a single-runtime asyncio stack keeps every
  per-node daemon on one event loop (this box schedules everything on few
  cores; the reference's dedicated poller threads would only add contention).
- Every process runs at most one IO event loop in a background thread
  (:class:`IoContext`), mirroring the reference's instrumented io_context per
  component (src/ray/common/asio/instrumented_io_context.h).  Handler timings
  are recorded for debug dumps.
- ``RetryableRpcClient`` reconnects with exponential backoff until a deadline,
  like retryable_grpc_client.cc, and consults the chaos hooks
  (:mod:`ray_tpu.rpc.chaos`) on every call.
"""

from __future__ import annotations

import asyncio
import itertools
import pickle
import struct
import threading
import time
import traceback
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from ray_tpu.common.config import GLOBAL_CONFIG
from ray_tpu.common.retry import Deadline, RetryPolicy
from ray_tpu.common.status import RtConnectionError, RtTimeoutError
from . import chaos

_HEADER = struct.Struct("<IB")  # payload length, frame type
_FRAME_REQ = 1
_FRAME_RESP = 2
_FRAME_HELLO = 3  # version handshake (rpc/protocol.py)

# schema.validate, bound on first validated dispatch (schema imports parts
# of common/ that import this module — a boot-time cycle, not a real dep)
_validate = None

Address = Tuple[str, int]


class RpcError(RtConnectionError):
    pass


class RpcProtocolError(RpcError):
    """Version negotiation failed — NOT retryable (a peer speaking an
    incompatible protocol will not heal on reconnect)."""


class RpcMethodNotFound(RpcError):
    """Peer answered but doesn't serve this method — NOT retryable on the
    same connection (an unpromoted GCS standby looks exactly like this;
    rotating clients treat it as "not the leader, try the next address")."""


class RpcRetriesExhausted(RtTimeoutError):
    """Reconnect-with-backoff burned the whole per-address deadline — the
    peer is dead at the transport level, not merely slow.  Distinct from a
    plain per-call RtTimeoutError (slow-but-alive handler) so failover
    clients rotate only on the former."""


class RemoteMethodError(Exception):
    """Handler raised; carries the remote traceback."""

    def __init__(self, method: str, cause: BaseException, tb: str):
        self.method = method
        self.cause = cause
        self.tb = tb
        super().__init__(f"RPC handler {method!r} raised {cause!r}\n--- remote ---\n{tb}")


async def _read_frame(reader: asyncio.StreamReader):
    header = await reader.readexactly(_HEADER.size)
    length, ftype = _HEADER.unpack(header)
    body = await reader.readexactly(length)
    return ftype, pickle.loads(body)


def _write_frame(writer: asyncio.StreamWriter, ftype: int, msg: Any):
    body = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    writer.write(_HEADER.pack(len(body), ftype) + body)


class IoContext:
    """One background asyncio loop per process, shared by all clients/servers.

    Sync code submits coroutines with :meth:`run`; async code just uses the
    loop directly.  Named-handler timing stats mimic the reference's
    event_stats.cc so `debug_state` dumps show where loop time goes.
    """

    _singleton: Optional["IoContext"] = None
    _singleton_lock = threading.Lock()

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name="rt-io", daemon=True)
        self.stats: Dict[str, Tuple[int, float]] = {}
        self._stats_lock = threading.Lock()
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    @classmethod
    def current(cls) -> "IoContext":
        with cls._singleton_lock:
            if cls._singleton is None or not cls._singleton._thread.is_alive():
                cls._singleton = cls()
            return cls._singleton

    def run(self, coro: Awaitable, timeout: Optional[float] = None):
        """Block the calling (non-loop) thread on a coroutine."""
        import concurrent.futures as cf

        cfut: cf.Future = cf.Future()
        task_box: list = []

        def _do():
            task = self.spawn(coro)
            task_box.append(task)

            def _copy(t: asyncio.Task):
                if t.cancelled():
                    cfut.cancel()
                elif t.exception() is not None:
                    cfut.set_exception(t.exception())
                else:
                    cfut.set_result(t.result())

            task.add_done_callback(_copy)

        self.loop.call_soon_threadsafe(_do)
        try:
            return cfut.result(timeout)
        except cf.TimeoutError:
            if cfut.done():
                # TimeoutError raised BY the coroutine (cf.TimeoutError is
                # builtins.TimeoutError since 3.8): propagate it untouched
                # instead of mislabeling it as run()'s own wait expiring.
                raise
            # don't leave the coroutine running (and its side effects live)
            # after the caller has taken the timeout path
            self.loop.call_soon_threadsafe(
                lambda: task_box and task_box[0].cancel())
            raise RtTimeoutError(f"rpc timed out after {timeout}s")
        except cf.CancelledError:
            raise RtTimeoutError("operation cancelled")

    # The event loop holds only WEAK references to tasks; any fire-and-forget
    # task must be pinned here or the GC can destroy it mid-await ("Task was
    # destroyed but it is pending!"), silently dropping RPCs.
    _pinned_tasks: set = set()

    def spawn(self, coro) -> "asyncio.Task":
        """ensure_future with a strong reference for the task's lifetime.
        Must be called from the loop thread."""
        task = asyncio.ensure_future(coro)
        IoContext._pinned_tasks.add(task)
        task.add_done_callback(IoContext._pinned_tasks.discard)
        return task

    def spawn_threadsafe(self, coro):
        """Spawn from any thread; fire-and-forget."""
        def _do():
            self.spawn(coro)
        self.loop.call_soon_threadsafe(_do)

    def record(self, name: str, elapsed: float):
        with self._stats_lock:
            count, total = self.stats.get(name, (0, 0.0))
            self.stats[name] = (count + 1, total + elapsed)


def _schema_validation_enabled() -> bool:
    """Wire-contract validation (rpc/schema.py). Reads the config registry
    each time — GLOBAL_CONFIG caches internally and reset_cache()/
    system_config propagation must be able to flip the knob at runtime
    (a process-global cache here would pin the boot-time value)."""
    try:
        return bool(GLOBAL_CONFIG.get("rpc_schema_validation"))
    except Exception:  # noqa: BLE001
        return True


class RpcServer:
    """Registers async handlers by method name; serves framed requests.

    Handlers: ``async def handler(**kwargs) -> result``.  Results/exceptions
    are pickled back.  One connection carries many concurrent requests.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 validate_schemas: bool = True):
        self.host = host
        self.port = port
        # Core services share one method namespace with the wire-schema
        # table; servers whose methods collide by NAME but not by contract
        # (e.g. the ray:// session driver's create_actor) opt out.
        self.validate_schemas = validate_schemas
        self._handlers: Dict[str, Callable[..., Awaitable[Any]]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._io = IoContext.current()
        self._conns: set = set()

    def register(self, method: str, handler: Callable[..., Awaitable[Any]]):
        self._handlers[method] = handler

    def register_service(self, service: object, prefix: str = ""):
        """Register every public async method of `service`."""
        for name in dir(service):
            if name.startswith("_"):
                continue
            fn = getattr(service, name)
            if callable(fn) and asyncio.iscoroutinefunction(fn):
                self.register(prefix + name, fn)

    @property
    def address(self) -> Address:
        return (self.host, self.port)

    def start(self):
        self._io.run(self._start())

    async def _start(self):
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._conns.add(writer)
        write_lock = asyncio.Lock()
        # a connection whose first frame is a REQ (no HELLO) is a legacy
        # peer: served as protocol 1 (rpc/protocol.py rolling-upgrade path)
        peer_protocol = 1
        try:
            while True:
                ftype, msg = await _read_frame(reader)
                if ftype == _FRAME_HELLO:
                    from ray_tpu.rpc import protocol as _proto

                    from ray_tpu.rpc.schema import SCHEMA_VERSION

                    hello = {"protocol": _proto.PROTOCOL_VERSION,
                             "min_protocol": _proto.MIN_SUPPORTED_PROTOCOL,
                             "schema": SCHEMA_VERSION}
                    try:
                        peer_protocol = _proto.negotiate(
                            int(msg.get("protocol", 1)),
                            int(msg.get("min_protocol", 1)))
                    except _proto.ProtocolError as e:
                        hello["error"] = str(e)
                        async with write_lock:
                            _write_frame(writer, _FRAME_HELLO, hello)
                            await writer.drain()
                        return  # finally: close the incompatible peer
                    async with write_lock:
                        _write_frame(writer, _FRAME_HELLO, hello)
                        await writer.drain()
                    continue
                if ftype != _FRAME_REQ:
                    continue
                self._io.spawn(
                    self._dispatch(msg, writer, write_lock, peer_protocol))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, msg: dict, writer: asyncio.StreamWriter,
                        write_lock: asyncio.Lock, peer_protocol: int = 1):
        req_id, method, kwargs = msg["id"], msg["method"], msg["kwargs"]
        start = time.monotonic()
        handler = self._handlers.get(method)
        if handler is None:
            reply = {"id": req_id, "error": ("nomethod", f"unknown method {method!r}", "")}
        else:
            try:
                if self.validate_schemas and _schema_validation_enabled():
                    global _validate
                    if _validate is None:
                        from ray_tpu.rpc.schema import validate as _validate
                    # the request's own stamp (if any) can only lower the
                    # connection-negotiated version, never raise it
                    v = min(peer_protocol, int(msg.get("v", peer_protocol)))
                    kwargs = _validate(method, kwargs, peer_protocol=v)
                result = await handler(**kwargs)
                reply = {"id": req_id, "result": result}
            except Exception as e:  # noqa: BLE001 - handler errors go to caller
                reply = {"id": req_id, "error": ("raised", e, traceback.format_exc())}
        self._io.record(f"rpc.{method}", time.monotonic() - start)
        async with write_lock:
            try:
                _write_frame(writer, _FRAME_RESP, reply)
                await writer.drain()
            except (ConnectionError, OSError) as e:
                import logging
                logging.getLogger(__name__).warning("reply write for %s failed: %s", method, e)
            except Exception:  # unpicklable result/exception: degrade to string
                try:
                    detail = repr(reply.get("result", reply.get("error")))
                    _write_frame(
                        writer,
                        _FRAME_RESP,
                        {"id": req_id, "error": ("unserializable", detail, "")},
                    )
                    await writer.drain()
                except (ConnectionError, OSError):
                    pass

    def stop(self):
        if self._server is not None:
            self._io.run(self._stop())

    async def _stop(self):
        assert self._server is not None
        self._server.close()
        for w in list(self._conns):
            try:
                w.close()
            except Exception:
                pass
        await self._server.wait_closed()
        self._server = None


class RpcClient:
    """Single-connection multiplexed client. Not retryable; see RetryableRpcClient."""

    def __init__(self, address: Address):
        self.address = tuple(address)
        self._io = IoContext.current()
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._ids = itertools.count(1)
        self._conn_lock: Optional[asyncio.Lock] = None
        self._write_lock: Optional[asyncio.Lock] = None
        self._hello_fut: Optional[asyncio.Future] = None
        # what this connection speaks after negotiation (protocol.py)
        self.negotiated_protocol: Optional[int] = None

    async def _ensure_connected(self):
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
            self._write_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None:
                return
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(*self.address),
                    GLOBAL_CONFIG.get("rpc_connect_timeout_s"),
                )
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                raise RpcError(f"connect to {self.address} failed: {e}") from e
            self._writer = writer
            self._io.spawn(self._read_loop(reader))
            await self._handshake(writer)

    async def _handshake(self, writer: asyncio.StreamWriter):
        """First frames on the wire: HELLO out, HELLO back (protocol.py).
        Completes before any request is written.

        A pre-handshake (protocol-1) server drops the unknown HELLO frame
        without replying.  With ``rpc_require_hello=False`` (rolling-
        upgrade mode) a HELLO timeout on an otherwise-live connection is
        therefore read as "legacy peer" and the connection degrades to
        protocol 1 (the new-client→old-server half of the contract;
        old-client→new-server is the server's REQ-first path), remembered
        so reconnects skip the wait.  By default the flag is True and the
        timeout is a transport failure — a wedged-but-accepting NEW server
        must keep triggering retry/rotation (GCS failover), not a silent
        permanent downgrade."""
        from ray_tpu.rpc import protocol as _proto

        if getattr(self, "_peer_is_legacy", False):
            self.negotiated_protocol = 1
            return
        self._hello_fut = asyncio.get_running_loop().create_future()
        try:
            from ray_tpu.rpc.schema import SCHEMA_VERSION

            _write_frame(writer, _FRAME_HELLO,
                         {"protocol": _proto.PROTOCOL_VERSION,
                          "min_protocol": _proto.MIN_SUPPORTED_PROTOCOL,
                          "schema": SCHEMA_VERSION})
            await writer.drain()
            hello = await asyncio.wait_for(
                self._hello_fut, GLOBAL_CONFIG.get("rpc_connect_timeout_s"))
        except asyncio.TimeoutError as e:
            if not GLOBAL_CONFIG.get("rpc_require_hello"):
                # rolling-upgrade mode: live connection, no HELLO back —
                # assume legacy protocol-1 server
                self._peer_is_legacy = True
                self.negotiated_protocol = 1
                self._hello_fut = None
                return
            self._fail_all(RpcError(f"handshake with {self.address} failed"))
            raise RpcError(
                f"handshake with {self.address} timed out: {e}") from e
        except (ConnectionError, OSError) as e:
            self._fail_all(RpcError(f"handshake with {self.address} failed"))
            raise RpcError(
                f"handshake with {self.address} failed: {e}") from e
        finally:
            self._hello_fut = None
        if "error" in hello:
            self._fail_all(RpcProtocolError(str(hello["error"])))
            raise RpcProtocolError(
                f"protocol negotiation with {self.address} failed: "
                f"{hello['error']}")
        try:
            self.negotiated_protocol = _proto.negotiate(
                int(hello.get("protocol", 1)),
                int(hello.get("min_protocol", 1)))
        except _proto.ProtocolError as e:
            raise RpcProtocolError(
                f"protocol negotiation with {self.address} failed: {e}"
            ) from e

    async def _read_loop(self, reader: asyncio.StreamReader):
        try:
            while True:
                ftype, msg = await _read_frame(reader)
                if ftype == _FRAME_HELLO:
                    fut = self._hello_fut
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
                    continue
                fut = self._pending.pop(msg["id"], None)
                if fut is not None and not fut.done():
                    if "error" in msg:
                        kind, cause, tb = msg["error"]
                        if kind == "raised" and isinstance(cause, BaseException):
                            fut.set_exception(RemoteMethodError(msg.get("method", "?"), cause, tb))
                        elif kind == "nomethod":
                            # typed so callers (and the retry loop) can tell
                            # "peer doesn't serve this" from transport failure
                            fut.set_exception(RpcMethodNotFound(str(cause)))
                        else:
                            fut.set_exception(RpcError(f"{kind}: {cause}"))
                    else:
                        fut.set_result(msg.get("result"))
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            self._fail_all(RpcError(f"connection to {self.address} lost: {e}"))
        except Exception as e:  # noqa: BLE001 - corrupt frame: surface loudly
            import logging
            logging.getLogger(__name__).exception("read loop died: %s", e)
            self._fail_all(RpcError(f"read loop on {self.address} died: {e}"))

    def _fail_all(self, exc: Exception):
        self._writer = None
        hello = self._hello_fut
        if hello is not None and not hello.done():
            hello.set_exception(exc)
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)

    async def call_async(self, method: str, timeout: Optional[float] = None, **kwargs):
        fail_req, fail_resp = chaos.maybe_inject_failure(method)
        if fail_req:
            raise chaos.RpcChaosError(f"injected request failure for {method}")
        await self._ensure_connected()
        req_id = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        async with self._write_lock:
            writer = self._writer
            if writer is None:  # connection died while we awaited the lock
                self._pending.pop(req_id, None)
                raise RpcError(f"connection to {self.address} lost before write")
            try:
                _write_frame(writer, _FRAME_REQ,
                             {"id": req_id, "method": method,
                              "kwargs": kwargs,
                              "v": self.negotiated_protocol or 1})
                await writer.drain()
            except (ConnectionError, OSError) as e:
                self._pending.pop(req_id, None)
                self._fail_all(RpcError(f"write to {self.address} failed: {e}"))
                raise RpcError(f"write to {self.address} failed: {e}") from e
        try:
            result = await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._pending.pop(req_id, None)
            raise RtTimeoutError(f"rpc {method} to {self.address} timed out")
        except BaseException:  # incl. outer cancellation: don't leak the pending slot
            self._pending.pop(req_id, None)
            raise
        if fail_resp:
            raise chaos.RpcChaosError(f"injected response failure for {method}")
        return result

    def call(self, method: str, timeout: Optional[float] = None, **kwargs):
        return self._io.run(self.call_async(method, timeout=timeout, **kwargs), timeout)

    def close(self):
        writer, self._writer = self._writer, None
        if writer is not None:
            def _close():
                try:
                    writer.close()
                except Exception:
                    pass
            self._io.loop.call_soon_threadsafe(_close)


class RetryableRpcClient:
    """Retries connection-level failures with exponential backoff until a
    deadline (reference: retryable_grpc_client.cc).  Handler-raised exceptions
    are NOT retried — they are application errors."""

    def __init__(self, address: Address, max_attempts: int = 1 << 30, deadline_s: Optional[float] = None,
                 abort_check=None):
        self.address = tuple(address)
        self._client = RpcClient(address)
        self._max_attempts = max_attempts
        # Bounded by default: without a deadline, a dead peer would otherwise
        # be retried forever (reference bounds this with
        # gcs_rpc_server_reconnect_timeout_s).
        if deadline_s is None:
            deadline_s = float(GLOBAL_CONFIG.get("gcs_rpc_server_reconnect_timeout_s"))
        self._deadline_s = deadline_s
        # Optional async predicate consulted after each connection-level
        # failure: True = the peer is confirmed permanently gone (e.g. its
        # raylet reaped the process), so reconnecting cannot help — fail
        # now instead of burning the remaining deadline.
        self._abort_check = abort_check

    async def call_async(self, method: str, timeout: Optional[float] = None, **kwargs):
        policy = RetryPolicy(
            base_s=GLOBAL_CONFIG.get("rpc_retry_base_ms") / 1000.0,
            cap_s=GLOBAL_CONFIG.get("rpc_retry_max_ms") / 1000.0,
            deadline=Deadline(self._deadline_s))
        attempt = 0
        while True:
            try:
                return await self._client.call_async(method, timeout=timeout, **kwargs)
            except (RpcProtocolError, RpcMethodNotFound):
                raise  # neither heals on reconnect to the same peer
            except (RpcError, chaos.RpcChaosError) as e:
                attempt += 1
                if attempt >= self._max_attempts:
                    raise
                if self._abort_check is not None and await self._abort_check(e):
                    raise
                if not await policy.asleep(attempt):
                    # per-address reconnect budget spent: typed so failover
                    # clients rotate and plain callers see "peer is dead"
                    raise RpcRetriesExhausted(
                        f"rpc {method} retries exhausted: {e}") from e
                self._client.close()
                self._client = RpcClient(self.address)

    def call(self, method: str, timeout: Optional[float] = None, **kwargs):
        return IoContext.current().run(self.call_async(method, timeout=timeout, **kwargs))

    def close(self):
        self._client.close()
