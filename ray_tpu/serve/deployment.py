"""Deployment descriptor (reference ``python/ray/serve/deployment.py``).

``@serve.deployment`` wraps a class (or function) with replica/resource/
autoscaling options; ``.bind(*args)`` produces an Application ready for
``serve.run``. Replicas are plain actors; the callable convention is
``__call__`` (functions are auto-wrapped).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple


@dataclasses.dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_threshold: float = 1.25    # scale up when load > target*this
    downscale_threshold: float = 0.5   # scale down when load < target*this


@dataclasses.dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    ray_actor_options: Dict[str, Any] = dataclasses.field(
        default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    # HTTP ingress mount point (reference: Deployment.route_prefix);
    # None → "/<name>" at serve.run time.
    route_prefix: Optional[str] = None
    # "pow2" (power-of-two-choices) or "prefix_aware": requests whose
    # first argument shares a prefix route to the same replica so its
    # engine-side prefix cache hits (reference: serve request_router/
    # prefix-aware router over vLLM's prefix caching).
    request_router: str = "pow2"
    # How long a draining replica (redeploy, downscale, health ejection)
    # may finish in-flight work — including open SSE streams — before the
    # controller kills it (reference: graceful_shutdown_timeout_s).
    graceful_shutdown_timeout_s: float = 10.0

    def options(self, **kwargs) -> "Deployment":
        return dataclasses.replace(self, **kwargs)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


@dataclasses.dataclass
class Application:
    deployment: Deployment
    init_args: Tuple
    init_kwargs: Dict[str, Any]


class _FunctionReplica:
    """Adapter: function deployments become single-method callables."""

    def __init__(self, fn: Callable):
        self._fn = fn

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def make_deployment(func_or_class=None, *, name: Optional[str] = None,
                    num_replicas: int = 1, max_ongoing_requests: int = 8,
                    ray_actor_options: Optional[dict] = None,
                    autoscaling_config: Optional[dict] = None,
                    route_prefix: Optional[str] = None,
                    request_router: str = "pow2",
                    graceful_shutdown_timeout_s: float = 10.0) -> Any:
    def wrap(target):
        import functools

        cls = target
        if not isinstance(target, type):
            cls = functools.partial(_FunctionReplica, target)
            cls.__name__ = getattr(target, "__name__", "function_deployment")
        asc = autoscaling_config
        if isinstance(asc, dict):
            asc = AutoscalingConfig(**asc)
        return Deployment(
            func_or_class=cls,
            name=name or getattr(target, "__name__", "deployment"),
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=dict(ray_actor_options or {}),
            autoscaling_config=asc,
            route_prefix=route_prefix,
            request_router=request_router,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
        )

    if func_or_class is not None:
        return wrap(func_or_class)
    return wrap
