"""LLM serving: continuous batching on a TPU replica.

Reference delegates this wholesale to vLLM
(``python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py``);
here it's native: an Orca-style engine loop over the slot-based KV cache
(:mod:`ray_tpu.models.decoding`) — admit waiting requests into free slots
(bucketed prefill), then advance ALL active slots one token per jitted
decode step. Batched decode keeps the MXU busy across requests; fixed
shapes mean two compiled programs total (prefill per bucket + one decode).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class _Request:
    prompt: List[int]
    max_tokens: int
    temperature: float
    eos_token: Optional[int]
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    output: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    enqueued_at: float = dataclasses.field(default_factory=time.monotonic)
    # KV handed off from a prefill replica (PD disaggregation): dict with
    # "k"/"v" (layers, len, kv_heads, hd) numpy + "logits" of the last
    # prompt token; admission injects instead of prefilling.
    preload: Optional[dict] = None
    # per-request speculation override: None = engine default;
    # {"enabled": bool, "k": Optional[int]} normalized by _parse_req_spec
    spec: Optional[dict] = None
    # multi-tenant identity: per-tenant fair-share admission on decode
    # slots and on the radix-cache insert budget key off this
    tenant: Optional[str] = None
    # set by LLMEngine.cancel (replica-side abort): the engine thread
    # notices at its next finish check and frees the slot + blocks
    cancelled: bool = False


def _parse_req_spec(speculation) -> Optional[dict]:
    """Normalize a per-request speculation override (None / bool / dict
    with "enabled" and/or "k").

    Overrides only restrict what the engine already does: on an engine
    built without speculation they are validated then no-ops (clients
    need not know replica config to send requests), and a requested k
    above the engine's spec_k clamps to spec_k (the compiled verify
    window is sized at engine build)."""
    if speculation is None:
        return None
    if isinstance(speculation, bool):
        return {"enabled": speculation, "k": None}
    if isinstance(speculation, dict):
        unknown = set(speculation) - {"enabled", "k"}
        if unknown:
            raise ValueError(
                f"per-request speculation has unknown fields "
                f"{sorted(unknown)}; overridable: ['enabled', 'k']")
        k = speculation.get("k")
        if k is not None and int(k) <= 0:
            raise ValueError("per-request speculation k must be positive")
        return {"enabled": bool(speculation.get("enabled", True)),
                "k": None if k is None else int(k)}
    raise ValueError("per-request speculation must be a bool or dict")


class LLMEngine:
    """Single-replica continuous-batching engine.

    ``kv_cache="paged"`` (default) backs the slots with the block-table
    pool of :mod:`ray_tpu.models.paged_cache`: HBM per request tracks
    tokens actually cached, ``kv_pool_tokens`` bounds the total, and a
    request that outgrows the pool preempts the youngest other slot
    (vLLM-style recompute preemption: its blocks are freed and it
    re-queues with prompt+generated-so-far as the new prompt).
    ``kv_cache="slot"`` keeps the flat per-slot ``max_seq`` reservation.
    """

    def __init__(self, config=None, params=None, *, num_slots: int = 8,
                 max_seq: Optional[int] = None, model: str = "tiny",
                 seed: int = 0, prefix_cache_size: int = 0,
                 prefix_cache: Optional[str] = None,
                 prefix_cache_bytes: Optional[int] = None,
                 kv_cache: str = "paged",
                 kv_pool_tokens: Optional[int] = None,
                 kv_block_size: int = 64,
                 prefill_chunk: Optional[int] = None,
                 speculation=None,
                 spec_k: int = 4):
        import collections
        import os

        import jax

        from ray_tpu.models import llama
        from ray_tpu.models.decoding import (
            init_cache, make_batched_spec_verify, make_chunked_prefill,
            make_decode_step, make_inject, make_prefill)

        self.config = config or llama.CONFIGS[model]
        if params is None:
            params = llama.init_params(self.config, jax.random.key(seed))
        self.params = params
        self.num_slots = num_slots
        self.max_seq = max_seq or self.config.max_seq
        if kv_cache not in ("paged", "slot"):
            raise ValueError(f"kv_cache={kv_cache!r}: 'paged' or 'slot'")
        if kv_cache == "paged" and (kv_block_size <= 0
                                    or 2048 % kv_block_size):
            # must divide the prompt padding buckets or _prompt_pad can
            # return a non-multiple and crash every prefill
            raise ValueError(
                f"kv_block_size={kv_block_size} must divide 2048")
        self.kv_cache = kv_cache
        if kv_cache == "paged":
            from ray_tpu.models.paged_cache import (
                BlockAllocator, PagedConfig, init_paged_cache,
                make_paged_decode_step, make_paged_inject,
                make_paged_prefill)

            pool_tokens = kv_pool_tokens or num_slots * self.max_seq
            num_blocks = 1 + -(-pool_tokens // kv_block_size)  # +null
            self._page = PagedConfig(num_blocks=num_blocks,
                                     block_size=kv_block_size,
                                     max_seq=self.max_seq)
            self._alloc = BlockAllocator(self._page, num_slots)
            self._cache = init_paged_cache(self.config, self._page,
                                           num_slots)
            self._decode = make_paged_decode_step(params, self.config,
                                                  self._page)
            self._prefill = make_paged_prefill(params, self.config,
                                               self._page)
            self._inject = make_paged_inject(self.config, self._page)
        else:
            self._cache = init_cache(self.config, num_slots, self.max_seq)
            self._decode = make_decode_step(params, self.config)
            self._prefill = make_prefill(params, self.config)
            self._inject = make_inject(self.config)
        # Chunked prefill (vLLM-class / Sarathi): prompts longer than the
        # chunk prefill one fixed-size chunk per engine iteration,
        # interleaved with decode steps of the other slots — a long
        # prompt no longer stalls everyone's TTFT for its whole prefill.
        self._chunk_prefill = None
        if prefill_chunk is not None:
            if prefill_chunk <= 0:
                raise ValueError("prefill_chunk must be positive")
            if kv_cache == "paged":
                if prefill_chunk % kv_block_size:
                    raise ValueError(
                        f"prefill_chunk={prefill_chunk} must be a "
                        f"multiple of kv_block_size={kv_block_size}")
                from ray_tpu.models.paged_cache import \
                    make_chunked_paged_prefill

                self._chunk_prefill = make_chunked_paged_prefill(
                    params, self.config, self._page)
            else:
                self._chunk_prefill = make_chunked_prefill(
                    params, self.config)
        self.prefill_chunk = prefill_chunk
        # slot -> {"req", "tokens", "pos"} for in-progress chunked prefills
        self._prefilling: Dict[int, dict] = {}
        self._chunks_run = 0
        # Speculative decoding (ray_tpu.models.speculation): a pluggable
        # proposer ("ngram" prompt lookup or a small "draft" model in
        # lockstep) guesses up to k tokens per slot and ONE batched
        # verify forward scores every slot's window — per-slot under
        # continuous batching; slots without proposals degenerate to a
        # plain decode row in the same program. Greedy acceptance only
        # skips compute, never changes outputs; temperature > 0 keeps
        # the target distribution via residual resampling.
        self._proposer = None
        self._spec_cfg = None
        if speculation is not None:
            from ray_tpu.models.speculation import (SpeculationConfig,
                                                    make_length_installer)

            cfg = SpeculationConfig.parse(speculation, default_k=spec_k)
            if kv_cache != "slot":
                raise ValueError(
                    "speculation currently requires kv_cache='slot'")
            import jax
            import jax.numpy as jnp

            self._spec_cfg = cfg
            self._spec_verify = make_batched_spec_verify(params,
                                                         self.config)
            self._spec_fix_len = make_length_installer()
            # device-side argmax so greedy verify rounds transfer (B, C)
            # ids instead of (B, C, vocab) logits
            self._spec_argmax = jax.jit(
                lambda logits: jnp.argmax(logits, axis=-1))
            self._proposer = cfg.build_proposer(
                self.config, num_slots=num_slots, max_seq=self.max_seq)
            spec_k = cfg.k
            speculation = cfg.method
        self.speculation = speculation
        self.spec_k = spec_k
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._key = jax.random.key(seed)
        # Prefix reuse across requests, OFF by default. Two modes behind
        # one knob (prefix_cache / RT_prefix_cache env):
        #   "radix"  — default when enabled on a paged engine: the radix
        #              tree of ray_tpu.models.prefix_cache shares the
        #              prompt's pool blocks read-only between requests
        #              (block-level, zero-copy, copy-on-write divergence)
        #              so a shared-system-prompt request prefills ONLY
        #              its new tokens.
        #   "legacy" — the old exact-match full-prompt host cache, kept
        #              as a parity oracle: hits re-inject a device->host
        #              KV copy. Only an identical prompt can ever hit.
        # Both modes share ONE byte budget (prefix_cache_bytes); the
        # legacy count cap (prefix_cache_size) additionally applies so
        # old configs keep their behavior.
        mode = prefix_cache
        if mode is None:
            mode = os.environ.get("RT_prefix_cache")
        if mode is None:
            if prefix_cache_size > 0 or (prefix_cache_bytes or 0) > 0:
                mode = "radix" if kv_cache == "paged" else "legacy"
            else:
                mode = "off"
        if mode not in ("radix", "legacy", "off"):
            raise ValueError(
                f"prefix_cache={mode!r}: 'radix', 'legacy' or 'off'")
        if mode == "radix" and kv_cache != "paged":
            raise ValueError("prefix_cache='radix' requires "
                             "kv_cache='paged' (it shares pool blocks)")
        self._prefix_mode = mode
        self._prefix_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._prefix_cache_size = prefix_cache_size
        self._prefix_cache_hostbytes = 0
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefix_match_faults = 0
        self._prefix_insert_faults = 0
        self._fair_share_skips = 0
        self._radix = None
        if mode == "radix":
            from ray_tpu.models.paged_cache import (make_block_copy,
                                                    make_chunked_paged_prefill)
            from ray_tpu.models.prefix_cache import RadixPrefixCache

            c = self.config
            itemsize = self._cache["k"].dtype.itemsize
            bytes_per_block = (2 * c.n_layers * kv_block_size
                               * c.n_kv_heads * c.head_dim * itemsize)
            if prefix_cache_bytes is None:
                # default: the tree may cache up to half the pool —
                # pool-pressure eviction reclaims cold blocks anyway,
                # the budget just bounds steady-state residency
                prefix_cache_bytes = ((self._page.num_blocks - 1) // 2
                                      * bytes_per_block)
            self._radix = RadixPrefixCache(
                self._alloc, bytes_per_block=bytes_per_block,
                budget_bytes=prefix_cache_bytes)
            self._block_copy = make_block_copy(self.config, self._page)
            if self._chunk_prefill is None:
                # suffix-only prefill after a radix hit rides the chunked
                # kernel (row-level scatter, arbitrary start) even when
                # the engine wasn't configured for chunked prefill
                self._chunk_prefill = make_chunked_paged_prefill(
                    params, self.config, self._page)
        elif mode == "legacy" and prefix_cache_bytes is None:
            prefix_cache_bytes = 64 << 20   # footgun fix: bytes, not
            # just entry count — a handful of long prompts used to pin
            # unbounded full k/v host arrays
        self._prefix_cache_bytes = prefix_cache_bytes or 0

        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._waiting: "collections.deque[_Request]" = collections.deque()
        self._pending: Dict[str, dict] = {}      # streaming submit/poll
        self._pending_lock = threading.Lock()
        self._slots: List[Optional[_Request]] = [None] * num_slots
        self._last_token = np.zeros(num_slots, np.int32)
        # host mirror of cached tokens per slot (= device cache length)
        self._slot_len = np.zeros(num_slots, np.int64)
        self._admit_seq = np.zeros(num_slots, np.int64)  # preempt-victim age
        self._admit_counter = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-engine")
        self._thread.start()
        self._steps = 0
        self._tokens_generated = 0
        self._preemptions = 0

    # ------------------------------------------------------------- public
    def _check_vocab(self, prompt: List[int]) -> None:
        """Reject out-of-vocab prompt token ids at submission. On device
        the embed gather would clamp silently, but host-side speculation
        indexes probability rows by proposed token — and an ngram
        proposer re-proposes PROMPT tokens, so one malformed request
        could crash an engine step shared by every in-flight slot."""
        V = self.config.vocab_size
        for t in prompt:
            if not 0 <= int(t) < V:
                raise ValueError(
                    f"prompt token {t} out of vocab range [0, {V})")

    def generate(self, prompt: List[int], max_tokens: int = 64,
                 temperature: float = 0.0,
                 eos_token: Optional[int] = None,
                 timeout_s: float = 300.0,
                 speculation=None, tenant: Optional[str] = None
                 ) -> List[int]:
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + max_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_tokens ({max_tokens}) "
                f"exceeds max_seq {self.max_seq}")
        self._check_vocab(prompt)
        req = _Request(list(prompt), max_tokens, temperature, eos_token,
                       spec=_parse_req_spec(speculation), tenant=tenant)
        self._queue.put(req)
        if not req.done.wait(timeout_s):
            raise TimeoutError("generation timed out")
        if req.error:
            raise RuntimeError(req.error)
        return req.output

    def submit(self, prompt: List[int], max_tokens: int = 64,
               temperature: float = 0.0,
               eos_token: Optional[int] = None,
               speculation=None, tenant: Optional[str] = None) -> str:
        """Enqueue without blocking; poll with :meth:`poll` (drives the
        proxy's SSE token streaming)."""
        import uuid

        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + max_tokens > self.max_seq:
            raise ValueError("prompt + max_tokens exceeds max_seq")
        self._check_vocab(prompt)
        req = _Request(list(prompt), max_tokens, temperature, eos_token,
                       spec=_parse_req_spec(speculation), tenant=tenant)
        rid = uuid.uuid4().hex
        with self._pending_lock:
            self._pending[rid] = {"req": req, "sent": 0}
        self._queue.put(req)
        return rid

    def cancel(self, request_id: str) -> bool:
        """Replica-side request abort: mark the request cancelled and
        drop its poll entry. The engine thread notices at its next
        finish check and frees the slot — including the refcount drop
        on any radix-shared blocks, which is why cancellation must
        never free blocks directly from the caller thread."""
        with self._pending_lock:
            ent = self._pending.pop(request_id, None)
        if ent is None:
            return False
        ent["req"].cancelled = True
        return True

    def submit_prefilled(self, prompt: List[int], k, v, logits,
                         max_tokens: int = 64, temperature: float = 0.0,
                         eos_token: Optional[int] = None) -> str:
        """Decode-side half of PD disaggregation: admit a request whose
        prompt KV was computed by a prefill replica. k/v are
        (layers, len(prompt), kv_heads, head_dim) arrays, logits the last
        prompt position's logits."""
        import uuid

        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + max_tokens > self.max_seq:
            raise ValueError("prompt + max_tokens exceeds max_seq")
        k, v = np.asarray(k), np.asarray(v)
        c = self.config
        want = (c.n_layers, len(prompt), c.n_kv_heads, c.head_dim)
        if k.shape != want or v.shape != want:
            # caller thread: surface the mismatch to the submitter rather
            # than blowing up the engine loop for every in-flight request
            raise ValueError(
                f"prefilled KV shape {k.shape}/{v.shape} != expected {want}")
        req = _Request(list(prompt), max_tokens, temperature, eos_token,
                       preload={"k": k, "v": v,
                                "logits": np.asarray(logits)})
        rid = uuid.uuid4().hex
        with self._pending_lock:
            self._pending[rid] = {"req": req, "sent": 0}
        self._queue.put(req)
        return rid

    def poll(self, request_id: str) -> Dict[str, Any]:
        """New tokens since the last poll + done flag. The entry is dropped
        once fully drained after completion."""
        with self._pending_lock:
            ent = self._pending.get(request_id)
            if ent is None:
                return {"chunks": [], "done": True}
            ent["last_poll"] = time.monotonic()
            req = ent["req"]
            out = list(req.output)   # snapshot (engine thread appends)
            chunks = out[ent["sent"]:]
            ent["sent"] = len(out)
            finished = req.done.is_set() and ent["sent"] >= len(req.output)
            if finished:
                del self._pending[request_id]
            if req.error:
                raise RuntimeError(req.error)
            return {"chunks": chunks, "done": finished}

    def stats(self) -> Dict[str, Any]:
        out = {"steps": self._steps,
               "tokens_generated": self._tokens_generated,
               "active_slots": sum(s is not None for s in self._slots),
               "queued": self._queue.qsize() + len(self._waiting),
               "prefix_hits": self._prefix_hits,
               "prefix_misses": self._prefix_misses,
               "prefill_chunks_run": self._chunks_run,
               "prefilling_slots": len(self._prefilling),
               "spec_proposed": self._spec_proposed,
               "spec_accepted": self._spec_accepted,
               "spec_acceptance_rate": (
                   round(self._spec_accepted / self._spec_proposed, 4)
                   if self._spec_proposed else None),
               "speculation": self.speculation,
               "kv_cache": self.kv_cache}
        if self._proposer is not None:
            out.update(self._proposer.stats())
        if self.kv_cache == "paged":
            out.update(
                preemptions=self._preemptions,
                kv_blocks_free=self._alloc.free_blocks(),
                kv_blocks_total=self._page.num_blocks - 1,
                kv_block_size=self._page.block_size)
        pc = {"mode": self._prefix_mode,
              "match_faults": self._prefix_match_faults,
              "insert_faults": self._prefix_insert_faults,
              "budget_bytes": self._prefix_cache_bytes}
        if self._radix is not None:
            pc.update(self._radix.stats())
            out["prefix_hits"] = pc["hits"]
            out["prefix_misses"] = pc["misses"]
        else:
            pc.update(entries=len(self._prefix_cache),
                      cached_bytes=self._prefix_cache_hostbytes)
        out["prefix_cache"] = pc
        out["fair_share_skips"] = self._fair_share_skips
        return out

    def prefix_digest(self) -> List[int]:
        """Compact advertisement of cached prefixes for prefix-aware
        routing: cumulative 16-token-chunk hashes in the handle's
        ``_RouterState._prefix_hashes`` scheme. Best-effort — the engine
        thread mutates the tree concurrently, so a torn walk returns a
        partial digest rather than an error (it is a routing hint)."""
        try:
            if self._radix is not None:
                return self._radix.digest()
            if self._prefix_mode == "legacy":
                from ray_tpu.serve.handle import _RouterState

                out = set()
                for key in list(self._prefix_cache):
                    out.update(_RouterState._prefix_hashes(list(key)))
                return sorted(out)[:128]
        except Exception:  # noqa: BLE001 — hint only, never a failure
            pass
        return []

    def shutdown(self):
        self._stop.set()
        self._thread.join(timeout=5)

    # ------------------------------------------------------------- engine
    def _prompt_pad(self, plen: int) -> int:
        """Bucketed padded prompt length (block-multiple when paged)."""
        from ray_tpu.models.decoding import pad_to_bucket
        from ray_tpu.models.paged_cache import pad_to_block_bucket

        if self.kv_cache == "paged":
            cap = self._page.max_blocks_per_seq * self._page.block_size
            return min(pad_to_block_bucket(plen, self._page.block_size),
                       cap)
        return min(pad_to_bucket(plen), self.max_seq)

    def _inject_kv(self, slot: int, k: np.ndarray, v: np.ndarray,
                   true_len: int):
        """Pad external KV rows to a bucket and write them into `slot`.
        Paged: the caller must have ensure()d blocks for ``true_len``."""
        import jax.numpy as jnp

        P = self._prompt_pad(true_len)
        pad = P - k.shape[1]
        if pad > 0:
            widths = ((0, 0), (0, pad), (0, 0), (0, 0))
            k = np.pad(k, widths)
            v = np.pad(v, widths)
        if self.kv_cache == "paged":
            self._cache = self._inject(self._cache,
                                       self._alloc.tables[slot],
                                       jnp.asarray(k), jnp.asarray(v),
                                       true_len, slot)
        else:
            self._cache = self._inject(self._cache, jnp.asarray(k),
                                       jnp.asarray(v), true_len, slot)

    def _extract_kv(self, slot: int, true_len: int):
        """Device→host copy of one slot's prompt KV (rows [0, true_len))."""
        import jax

        if self.kv_cache == "paged":
            from ray_tpu.models.paged_cache import extract_kv

            return extract_kv(self._cache, self._alloc, slot, true_len)
        k, v = jax.device_get((self._cache["k"][:, slot, :true_len],
                               self._cache["v"][:, slot, :true_len]))
        return np.asarray(k), np.asarray(v)

    def _free_slot(self) -> Optional[int]:
        for slot in range(self.num_slots):
            if self._slots[slot] is None:
                return slot
        return None

    def _pick_waiting(self) -> int:
        """Index into the waiting deque of the next request to admit.
        FIFO, with two exceptions: a preempted request (non-empty
        output) always resumes first, and under multi-tenant contention
        a tenant already holding its fair share of decode slots yields
        to the first under-share tenant in the queue — PR 18's
        per-client proxy fair share, extended down onto slots so one
        tenant's burst cannot monopolize the engine."""
        if len(self._waiting) == 1 or self._waiting[0].output:
            return 0
        held: Dict[Optional[str], int] = {}
        for s in range(self.num_slots):
            r = self._slots[s]
            if r is not None:
                held[r.tenant] = held.get(r.tenant, 0) + 1
        tenants = {r.tenant for r in self._waiting} | set(held)
        if len(tenants) <= 1:
            return 0
        share = max(1, self.num_slots // len(tenants))
        for i, r in enumerate(self._waiting):
            if held.get(r.tenant, 0) < share:
                if i:
                    self._fair_share_skips += 1
                return i
        return 0  # every tenant at/over share: work-conserving FIFO

    def _radix_match(self, full_prompt: List[int]):
        """Longest cached prefix of the prompt. All but the LAST prompt
        token is eligible, so the block where the suffix prefill and the
        first decode write land is always private — a shared block is
        never written. An injected serve.llm.prefix_match fault degrades
        to cold prefill with a typed counter, never a failed request."""
        from ray_tpu.common import faults

        try:
            faults.fault_point("serve.llm.prefix_match")
        except ConnectionError:
            self._prefix_match_faults += 1
            return None
        m = self._radix.match(full_prompt[:-1])
        return m if m.matched else None

    def _radix_insert(self, req: _Request, toks: List[int], slot: int):
        """Share the slot's full-block prefix into the radix tree —
        zero-copy: the tree increfs the slot's own blocks. Byte-budget
        and per-tenant-fair-share gated; an injected
        serve.llm.prefix_insert fault skips the insert with a typed
        counter (nothing is ever half-inserted)."""
        if self._radix is None or self.kv_cache != "paged":
            return
        from ray_tpu.common import faults

        try:
            faults.fault_point("serve.llm.prefix_insert")
        except ConnectionError:
            self._prefix_insert_faults += 1
            return
        bs = self._page.block_size
        nfull = min(len(toks) // bs,
                    int(np.count_nonzero(self._alloc.tables[slot])))
        if nfull <= 0:
            return
        max_new = None
        tb = self._radix.tenant_blocks
        tenants = set(tb) | {req.tenant}
        if len(tenants) > 1:
            # cache-insert fair share: with several tenants caching,
            # each may pin at most its share of the byte budget
            cap = max(1, self._radix.budget_blocks() // len(tenants))
            max_new = cap - tb.get(req.tenant, 0)
            if max_new <= 0:
                self._fair_share_skips += 1
                return
        blocks = [int(b) for b in self._alloc.tables[slot, :nfull]]
        self._radix.insert(toks[:nfull * bs], blocks, tenant=req.tenant,
                           max_new=max_new)

    def _legacy_insert(self, key, logits_np, slot: int, plen: int,
                       resumed: bool):
        """Exact-match host cache insert (legacy parity oracle), now
        under the SAME byte budget as the radix path: the old cache
        capped entry count only, so a handful of long prompts could pin
        unbounded full k/v host arrays."""
        if self._prefix_mode != "legacy" or resumed:
            return
        self._prefix_misses += 1
        k, v = self._extract_kv(slot, plen)
        self._prefix_cache[key] = {"k": k, "v": v, "logits": logits_np}
        self._prefix_cache_hostbytes += k.nbytes + v.nbytes
        while self._prefix_cache and (
                (self._prefix_cache_size > 0
                 and len(self._prefix_cache) > self._prefix_cache_size)
                or (self._prefix_cache_bytes > 0
                    and self._prefix_cache_hostbytes
                    > self._prefix_cache_bytes)):
            _, old = self._prefix_cache.popitem(last=False)
            self._prefix_cache_hostbytes -= (old["k"].nbytes
                                             + old["v"].nbytes)

    def _admit(self):
        import jax.numpy as jnp

        # drain the thread-safe queue into the FIFO admission deque
        while True:
            try:
                self._waiting.append(self._queue.get_nowait())
            except queue.Empty:
                break
        while self._waiting:
            slot = self._free_slot()
            if slot is None:
                return
            idx = self._pick_waiting()
            req = self._waiting[idx]
            if req.cancelled:
                del self._waiting[idx]
                req.done.set()
                continue
            # preempted requests resume by recomputing prompt+generated
            full_prompt = req.prompt + req.output
            plen = len(full_prompt)
            match = None
            if self.kv_cache == "paged":
                # ensure plen + 1: this iteration's decode step writes
                # the first generated token at position plen, which
                # lives in a NEW block when the prompt is block-aligned.
                total = self._alloc.blocks_for(plen + 1)
                if total > min(self._page.num_blocks - 1,
                               self._page.max_blocks_per_seq):
                    # can never fit, even with the pool idle: fail it
                    # rather than deadlock the queue
                    del self._waiting[idx]
                    req.error = (f"prompt of {plen} tokens exceeds KV "
                                 "pool capacity")
                    req.done.set()
                    continue
                if self._radix is not None and req.preload is None:
                    match = self._radix_match(full_prompt)
                shared = match.blocks if match is not None else []
                # watermark: beyond this request's blocks, keep one
                # growth block of headroom per already-active slot, or
                # admission starves running requests into preemption
                need = (total - len(shared)
                        + sum(s is not None for s in self._slots))
                if shared:
                    # pin the matched blocks FIRST: the pool-pressure
                    # eviction below must never reclaim them
                    self._alloc.adopt(slot, shared)
                if self._alloc.free_blocks() < need and \
                        self._radix is not None:
                    self._radix.evict_for(need - self._alloc.free_blocks())
                if self._alloc.free_blocks() < need or not \
                        self._alloc.ensure(slot, plen + 1):
                    self._alloc.release(slot)  # un-pin the match
                    return  # picked request waits for blocks (no bypass)
                if match is not None and match.cow is not None:
                    # copy-on-write at the divergence block: ensure()
                    # placed a private block at the first position past
                    # the shared prefix; device-copy the cached block's
                    # rows into it, so the suffix prefill can resume
                    # MID-BLOCK at the divergence offset while the
                    # cached original stays read-only for its other
                    # references.
                    self._cache = self._block_copy(
                        self._cache, match.cow[0],
                        int(self._alloc.tables[slot, len(shared)]))
            del self._waiting[idx]
            resumed = bool(req.output)
            matched = match.matched if match is not None else 0
            key = tuple(full_prompt)
            cached = None
            if (self._prefix_mode == "legacy" and req.preload is None
                    and not resumed):
                cached = self._prefix_cache.get(key)
            if req.preload is not None:
                # PD handoff: prompt KV computed by a prefill replica
                self._inject_kv(slot, req.preload["k"], req.preload["v"],
                                plen)
                logits_np = req.preload["logits"]
                req.preload = None  # free the host copy
            elif cached is not None:
                self._prefix_hits += 1
                self._prefix_cache.move_to_end(key)
                self._inject_kv(slot, cached["k"], cached["v"], plen)
                logits_np = cached["logits"]
            elif matched > 0:
                # radix hit: the adopted blocks already hold the prefix
                # KV — prefill ONLY the uncached suffix (TTFT tracks new
                # tokens, not prompt length). Rides the chunked-prefill
                # machinery so a long suffix still interleaves with the
                # other slots' decode.
                self._slots[slot] = req
                self._slot_len[slot] = 0
                self._admit_counter += 1
                self._admit_seq[slot] = self._admit_counter
                self._prefilling[slot] = {"req": req,
                                          "tokens": full_prompt,
                                          "pos": matched}
                continue
            elif (self.prefill_chunk is not None
                  and plen > self.prefill_chunk):
                # chunked prefill: register and let the engine loop
                # advance one chunk per iteration interleaved with other
                # slots' decode; the slot starts decoding after the last
                # chunk (see _advance_chunked_prefill)
                self._slots[slot] = req
                self._slot_len[slot] = 0
                self._admit_counter += 1
                self._admit_seq[slot] = self._admit_counter
                self._prefilling[slot] = {"req": req,
                                          "tokens": full_prompt, "pos": 0}
                continue
            else:
                # cap padding at max_seq: a prompt that fits must be admitted
                P = self._prompt_pad(plen)
                tokens = np.zeros((1, P), np.int32)
                tokens[0, :plen] = full_prompt
                if self.kv_cache == "paged":
                    self._cache, logits = self._prefill(
                        self._cache, self._alloc.tables[slot],
                        jnp.asarray(tokens), plen, slot)
                else:
                    self._cache, logits = self._prefill(
                        self._cache, jnp.asarray(tokens), plen, slot)
                logits_np = np.asarray(logits)
                self._legacy_insert(key, logits_np, slot, plen, resumed)
                if self._radix is not None:
                    self._radix_insert(req, full_prompt, slot)
            tok = self._sample(logits_np.reshape(1, -1), req.temperature)[0]
            req.output.append(int(tok))
            self._slots[slot] = req
            self._last_token[slot] = tok
            self._slot_len[slot] = plen
            self._admit_counter += 1
            self._admit_seq[slot] = self._admit_counter
            if self._proposer is not None:
                self._proposer.admit(slot, full_prompt)
            self._maybe_finish(slot)

    def _spec_decode_step(self, active: np.ndarray) -> bool:
        """One speculative iteration for ALL active slots: collect
        per-slot proposals, score every window in one batched verify,
        apply the acceptance rule per slot, and install the accepted
        lengths (target + proposer rollback). Slots with no proposal —
        lookup miss, per-request opt-out, window out of room — ride the
        same program as 1-token windows, i.e. a plain decode step.

        Returns False WITHOUT touching the cache when no slot has any
        proposal at all: every window would be 1 token, and the plain
        decode program is ~(k+1)x cheaper than the verify for the same
        result — the caller falls through to it. (Safe for the draft
        proposer too: empty proposals mean it ran zero decode steps, so
        there is nothing to roll back.)"""
        import jax.numpy as jnp

        from ray_tpu.models.speculation import (accept_greedy,
                                                accept_speculative)

        C = self.spec_k + 1
        infos: Dict[int, dict] = {}
        for slot in range(self.num_slots):
            if not active[slot]:
                continue
            req = self._slots[slot]
            start = int(self._slot_len[slot])
            k_req = self.spec_k
            if req.spec is not None:
                if not req.spec["enabled"]:
                    k_req = 0
                elif req.spec["k"] is not None:
                    k_req = min(req.spec["k"], self.spec_k)
            room = req.max_tokens - len(req.output)
            k_eff = max(0, min(k_req, room - 1,
                               self.max_seq - start - 1))
            infos[slot] = {"seq": req.prompt + req.output,
                           "target_len": start, "k": k_eff}
        proposals = self._proposer.propose(infos) if infos else {}
        if not any(proposals.get(slot) for slot in infos):
            return False
        buf = np.zeros((self.num_slots, C), np.int32)
        true_lens = np.zeros(self.num_slots, np.int32)
        starts = np.zeros(self.num_slots, np.int32)
        for slot, info in infos.items():
            props = proposals.get(slot) or []
            buf[slot, 0] = self._last_token[slot]
            buf[slot, 1:1 + len(props)] = props
            true_lens[slot] = 1 + len(props)
            starts[slot] = info["target_len"]
        self._cache, all_logits = self._spec_verify(
            self._cache, jnp.asarray(buf), true_lens, starts)
        # greedy slots need only the (B, C) argmax ids — ship the full
        # (B, C, vocab) logits off-device only when some slot samples
        # (a real vocab makes the difference ~(k+1)x the decode path's
        # per-step transfer)
        greedy_np = np.asarray(self._spec_argmax(all_logits))
        need_full = any(self._slots[s].temperature > 0.0 for s in infos)
        logits_np = np.asarray(all_logits) if need_full else None
        # post-increment BEFORE seeding, like _sample: seeding first
        # would reuse the stream the previous plain step sampled with,
        # correlating accept/reject draws with the token just emitted
        self._steps += 1
        rng = np.random.default_rng(self._steps)
        accepted_map: Dict[int, int] = {}
        touched = np.zeros(self.num_slots, bool)
        new_lens = np.zeros(self.num_slots, np.int32)
        for slot in sorted(infos):
            req = self._slots[slot]
            props = proposals.get(slot) or []
            if req.temperature <= 0.0:
                emitted, accepted = accept_greedy(
                    greedy_np[slot, :1 + len(props)], props)
            else:
                emitted, accepted = accept_speculative(
                    logits_np[slot, :1 + len(props)], props,
                    req.temperature, rng)
            self._spec_proposed += len(props)
            self._spec_accepted += accepted
            accepted_map[slot] = accepted
            # respect max_tokens and eos inside the speculative window
            room = req.max_tokens - len(req.output)
            emitted = emitted[:max(1, room)]
            if req.eos_token is not None and req.eos_token in emitted:
                emitted = emitted[:emitted.index(req.eos_token) + 1]
            req.output.extend(emitted)
            self._last_token[slot] = emitted[-1]
            # the last emitted token is pending (not yet cached), so the
            # accepted cache length is start + len(emitted); rejected
            # rows beyond it are invisible and get overwritten later
            new_len = int(starts[slot]) + len(emitted)
            self._slot_len[slot] = new_len
            touched[slot] = True
            new_lens[slot] = new_len
            self._tokens_generated += len(emitted)
        if touched.any():
            self._cache["length"] = self._spec_fix_len(
                self._cache["length"], jnp.asarray(new_lens),
                jnp.asarray(touched))
        self._proposer.after_verify(accepted_map)
        for slot in sorted(accepted_map):
            self._maybe_finish(slot)
        return True

    def _advance_chunked_prefill(self):
        """Run ONE chunk of the oldest in-progress chunked prefill; on
        the final chunk, sample the first token and activate the slot."""
        import jax.numpy as jnp

        slot = next(iter(self._prefilling))
        st = self._prefilling[slot]
        toks, pos, C = st["tokens"], st["pos"], self.prefill_chunk
        if C is None:
            # radix-suffix prefill on an engine without chunked prefill:
            # one call covering the whole uncached suffix
            C = self._prompt_pad(len(toks) - pos)
        n = min(C, len(toks) - pos)
        buf = np.zeros((1, C), np.int32)
        buf[0, :n] = toks[pos:pos + n]
        if self.kv_cache == "paged":
            self._cache, logits = self._chunk_prefill(
                self._cache, self._alloc.tables[slot], jnp.asarray(buf),
                n, pos, slot)
        else:
            self._cache, logits = self._chunk_prefill(
                self._cache, jnp.asarray(buf), n, pos, slot)
        self._chunks_run += 1
        st["pos"] = pos + n
        if st["pos"] < len(toks):
            return
        req = st["req"]
        del self._prefilling[slot]
        plen = len(toks)
        logits_np = np.asarray(logits)
        resumed = bool(req.output)
        self._legacy_insert(tuple(toks), logits_np, slot, plen, resumed)
        if self._radix is not None:
            self._radix_insert(req, toks, slot)
        tok = self._sample(logits_np.reshape(1, -1), req.temperature)[0]
        req.output.append(int(tok))
        self._last_token[slot] = tok
        self._slot_len[slot] = plen
        if self._proposer is not None:
            self._proposer.admit(slot, toks)
        self._maybe_finish(slot)

    def _sample(self, logits: np.ndarray, temperature: float) -> np.ndarray:
        if temperature <= 0.0:
            return logits.argmax(-1).astype(np.int32)
        z = logits / max(temperature, 1e-5)
        z = z - z.max(-1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(-1, keepdims=True)
        rng = np.random.default_rng(self._steps)
        return np.array([rng.choice(p.shape[-1], p=row) for row in p],
                        np.int32)

    def _maybe_finish(self, slot: int):
        req = self._slots[slot]
        if req is None:
            return
        done = (req.cancelled
                or len(req.output) >= req.max_tokens
                or (req.eos_token is not None and req.output
                    and req.output[-1] == req.eos_token)
                or len(req.prompt) + len(req.output) >= self.max_seq)
        if done:
            if self._radix is not None and not req.cancelled:
                # on completion, offer the whole cached sequence (prompt
                # + generated) to the tree: multi-turn conversations hit
                # on their own history. Zero-copy — the tree increfs the
                # blocks release() is about to drop its slot ref on.
                seq = (req.prompt + req.output)[:int(self._slot_len[slot])]
                self._radix_insert(req, seq, slot)
            req.done.set()
            self._slots[slot] = None
            if self._proposer is not None:
                self._proposer.release(slot)
            if self.kv_cache == "paged":
                self._alloc.release(slot)

    def _preempt(self, slot: int):
        """Recompute preemption: free the slot's blocks and put the
        request back at the HEAD of the admission queue; it resumes by
        prefilling prompt+generated-so-far (vLLM's recompute mode)."""
        req = self._slots[slot]
        self._slots[slot] = None
        self._alloc.release(slot)
        if self._proposer is not None:
            self._proposer.release(slot)
        # a mid-chunked-prefill victim restarts its prefill on re-admission
        self._prefilling.pop(slot, None)
        self._waiting.appendleft(req)
        self._preemptions += 1

    def _grow_active_slots(self) -> None:
        """Before a decode step each active slot needs its next token's
        block. On pool exhaustion, preempt the youngest other active
        slot; a slot alone in the pool preempts itself."""
        for slot in range(self.num_slots):
            if self._slots[slot] is None:
                continue
            while not self._alloc.ensure(slot, int(self._slot_len[slot]) + 1):
                # pool pressure order: evict cold cached prefixes (LRU,
                # refcount-0-only — a block any live slot references is
                # untouchable) BEFORE preempting a running request
                if self._radix is not None and self._radix.evict_for(1):
                    continue
                victims = [s for s in range(self.num_slots)
                           if s != slot and self._slots[s] is not None]
                if victims:
                    victim = max(victims, key=lambda s: self._admit_seq[s])
                else:
                    victim = slot
                self._preempt(victim)
                if victim == slot:
                    break

    def _loop(self):
        import logging
        import traceback

        while not self._stop.is_set():
            try:
                self._loop_once()
            except Exception as e:  # noqa: BLE001 — engine must survive
                logging.getLogger(__name__).error(
                    "engine step failed:\n%s", traceback.format_exc())
                # fail every active request rather than hanging them
                for slot in range(self.num_slots):
                    req = self._slots[slot]
                    if req is not None:
                        req.error = f"engine step failed: {e!r}"
                        req.done.set()
                        self._slots[slot] = None
                        if self.kv_cache == "paged":
                            # blocks would otherwise leak for good: only
                            # _maybe_finish/_preempt release them
                            self._alloc.release(slot)
                self._prefilling.clear()

    _PENDING_TTL_S = 180.0

    def _sweep_pending(self):
        """Drop submit/poll entries whose client stopped polling (stream
        abandoned mid-generation) so replicas don't leak per-request state."""
        now = time.monotonic()
        with self._pending_lock:
            stale = [rid for rid, ent in self._pending.items()
                     if now - ent.get("last_poll",
                                      ent["req"].enqueued_at)
                     > self._PENDING_TTL_S]
            for rid in stale:
                del self._pending[rid]

    def _loop_once(self):
        import jax.numpy as jnp

        self._steps_since_sweep = getattr(self, "_steps_since_sweep", 0) + 1
        if self._steps_since_sweep >= 500:
            self._steps_since_sweep = 0
            self._sweep_pending()
        # grow BEFORE admitting: otherwise a tight pool admits the queue
        # head (paying its prefill), then immediately preempts it as the
        # youngest slot to feed an older slot's growth — prefill thrash
        if self.kv_cache == "paged":
            self._grow_active_slots()
        self._admit()
        # one prefill chunk per iteration: bounded interference with the
        # decode of already-active slots (vLLM-class chunked prefill)
        if self._prefilling:
            self._advance_chunked_prefill()
        active = np.array([
            self._slots[s] is not None and s not in self._prefilling
            for s in range(self.num_slots)])
        if not active.any():
            if not self._prefilling:
                time.sleep(0.002)
            return
        if self._proposer is not None:
            # speculation replaces the decode step wholesale: every
            # active slot gets a verify window (1-token windows for
            # slots without proposals), per-slot under continuous
            # batching — mid-chunked-prefill slots stay masked out.
            # When NO slot has a proposal this iteration, fall through
            # to the plain (cheaper) decode program below instead.
            if self._spec_decode_step(active):
                return
        if self.kv_cache == "paged":
            self._cache, logits = self._decode(
                self._cache, self._alloc.device_tables(),
                jnp.asarray(self._last_token), jnp.asarray(active))
        else:
            self._cache, logits = self._decode(
                self._cache, jnp.asarray(self._last_token),
                jnp.asarray(active))
        logits_np = np.asarray(logits)
        self._steps += 1
        for slot in range(self.num_slots):
            req = self._slots[slot]
            if req is None or slot in self._prefilling:
                # mid-chunked-prefill slots were masked inactive in the
                # decode; their logits row is garbage — no sampling
                continue
            tok = self._sample(logits_np[slot][None], req.temperature)[0]
            req.output.append(int(tok))
            self._last_token[slot] = tok
            self._slot_len[slot] += 1
            self._tokens_generated += 1
            self._maybe_finish(slot)


class LLMServer:
    """Serve deployment wrapper: one engine per replica.

    Deploy with ``serve.deployment(LLMServer).options(
    ray_actor_options={"num_tpus": N})``; requests are token-id lists
    (tokenization is a host-side pre/post step, kept off the replica).
    """

    def __init__(self, model: str = "tiny", num_slots: int = 8,
                 max_seq: Optional[int] = None, **engine_kwargs):
        self.engine = LLMEngine(model=model, num_slots=num_slots,
                                max_seq=max_seq, **engine_kwargs)

    @staticmethod
    def _parse(prompt_or_request, kwargs: Dict[str, Any]):
        """Accept either direct args (handle calls) or a proxy Request whose
        JSON body is {"prompt": [...], "max_tokens": n, ...}."""
        from ray_tpu.serve.proxy import Request

        if isinstance(prompt_or_request, Request):
            body = prompt_or_request.json() or {}
            merged = {"max_tokens": body.get("max_tokens", 64),
                      "temperature": body.get("temperature", 0.0),
                      "eos_token": body.get("eos_token"),
                      "speculation": body.get("speculation"),
                      # tenant identity for engine-level fair share:
                      # body field wins, else the same x-client-id
                      # header the proxy's admission control keys on
                      "tenant": (body.get("tenant")
                                 or prompt_or_request.headers.get(
                                     "x-client-id"))}
            return body.get("prompt", []), merged
        return prompt_or_request, kwargs

    def __call__(self, prompt_or_request, **kwargs) -> List[int]:
        prompt, kw = self._parse(prompt_or_request, kwargs)
        return self.engine.generate(
            prompt, kw.get("max_tokens", 64), kw.get("temperature", 0.0),
            kw.get("eos_token"), speculation=kw.get("speculation"),
            tenant=kw.get("tenant"))

    def submit(self, prompt_or_request, **kwargs) -> str:
        prompt, kw = self._parse(prompt_or_request, kwargs)
        return self.engine.submit(
            prompt, kw.get("max_tokens", 64), kw.get("temperature", 0.0),
            kw.get("eos_token"), speculation=kw.get("speculation"),
            tenant=kw.get("tenant"))

    def poll(self, request_id: str) -> Dict[str, Any]:
        return self.engine.poll(request_id)

    def cancel(self, request_id: str) -> bool:
        return self.engine.cancel(request_id)

    def prefix_digest(self) -> List[int]:
        """Exported through the Replica harness → controller →
        router-refresh path so prefix-aware handles can route to the
        replica holding the longest cached prefix."""
        return self.engine.prefix_digest()

    def stream(self, prompt_or_request, **kwargs):
        """Generator-protocol streaming (round 11): tokens yield as the
        engine produces them, and the proxy's SSE path PUSHES each one to
        the client over the streaming-generator protocol — no proxy→
        replica poll RPCs.  The wait on the engine is replica-local (this
        generator runs on the replica's executor thread, never an event
        loop).  ``submit``/``poll`` stay for pre-generator callers."""
        prompt, kw = self._parse(prompt_or_request, kwargs)
        request_id = self.engine.submit(
            prompt, kw.get("max_tokens", 64), kw.get("temperature", 0.0),
            kw.get("eos_token"), speculation=kw.get("speculation"),
            tenant=kw.get("tenant"))
        from ray_tpu.serve.proxy import SSEBatch

        while True:
            st = self.engine.poll(request_id)
            chunks = st["chunks"]
            if len(chunks) == 1:
                yield chunks[0]
            elif chunks:
                # burst since the last engine poll: ONE streamed item (one
                # report RPC), fanned back out to per-token SSE events at
                # the proxy — per-token report RPCs were slower than the
                # old poll loop
                yield SSEBatch(chunks)
            if st["done"]:
                return
            time.sleep(0.005)

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()
