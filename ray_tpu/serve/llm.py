"""LLM serving: continuous batching on a TPU replica.

Reference delegates this wholesale to vLLM
(``python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py``);
here it's native: an Orca-style engine loop over the slot-based KV cache
(:mod:`ray_tpu.models.decoding`) — admit waiting requests into free slots
(bucketed prefill), then advance ALL active slots one token per jitted
decode step. Batched decode keeps the MXU busy across requests; fixed
shapes mean two compiled programs total (prefill per bucket + one decode).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class _Request:
    prompt: List[int]
    max_tokens: int
    temperature: float
    eos_token: Optional[int]
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    output: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    enqueued_at: float = dataclasses.field(default_factory=time.monotonic)
    # KV handed off from a prefill replica (PD disaggregation): dict with
    # "k"/"v" (layers, len, kv_heads, hd) numpy + "logits" of the last
    # prompt token; admission injects instead of prefilling.
    preload: Optional[dict] = None


class LLMEngine:
    """Single-replica continuous-batching engine."""

    def __init__(self, config=None, params=None, *, num_slots: int = 8,
                 max_seq: Optional[int] = None, model: str = "tiny",
                 seed: int = 0, prefix_cache_size: int = 0):
        import collections

        import jax

        from ray_tpu.models import llama
        from ray_tpu.models.decoding import (
            init_cache, make_decode_step, make_inject, make_prefill)

        self.config = config or llama.CONFIGS[model]
        if params is None:
            params = llama.init_params(self.config, jax.random.key(seed))
        self.params = params
        self.num_slots = num_slots
        self.max_seq = max_seq or self.config.max_seq
        self._cache = init_cache(self.config, num_slots, self.max_seq)
        self._decode = make_decode_step(params, self.config)
        self._prefill = make_prefill(params, self.config)
        self._inject = make_inject(self.config)
        self._key = jax.random.key(seed)
        # Exact-prompt KV cache (host LRU), OFF by default: storing pays
        # a device->host copy of the prompt KV per admission, worth it
        # only for repeat-prompt workloads (enable via prefix_cache_size,
        # pair with the handle's prefix_aware router). Repeat prompts
        # skip prefill entirely: KV + last logits are re-injected into a
        # free slot (reference: prefix-aware routing leans on vLLM's
        # automatic prefix caching; here the engine owns the cache).
        self._prefix_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._prefix_cache_size = prefix_cache_size
        self._prefix_hits = 0
        self._prefix_misses = 0

        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._pending: Dict[str, dict] = {}      # streaming submit/poll
        self._pending_lock = threading.Lock()
        self._slots: List[Optional[_Request]] = [None] * num_slots
        self._last_token = np.zeros(num_slots, np.int32)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-engine")
        self._thread.start()
        self._steps = 0
        self._tokens_generated = 0

    # ------------------------------------------------------------- public
    def generate(self, prompt: List[int], max_tokens: int = 64,
                 temperature: float = 0.0,
                 eos_token: Optional[int] = None,
                 timeout_s: float = 300.0) -> List[int]:
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + max_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_tokens ({max_tokens}) "
                f"exceeds max_seq {self.max_seq}")
        req = _Request(list(prompt), max_tokens, temperature, eos_token)
        self._queue.put(req)
        if not req.done.wait(timeout_s):
            raise TimeoutError("generation timed out")
        if req.error:
            raise RuntimeError(req.error)
        return req.output

    def submit(self, prompt: List[int], max_tokens: int = 64,
               temperature: float = 0.0,
               eos_token: Optional[int] = None) -> str:
        """Enqueue without blocking; poll with :meth:`poll` (drives the
        proxy's SSE token streaming)."""
        import uuid

        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + max_tokens > self.max_seq:
            raise ValueError("prompt + max_tokens exceeds max_seq")
        req = _Request(list(prompt), max_tokens, temperature, eos_token)
        rid = uuid.uuid4().hex
        with self._pending_lock:
            self._pending[rid] = {"req": req, "sent": 0}
        self._queue.put(req)
        return rid

    def submit_prefilled(self, prompt: List[int], k, v, logits,
                         max_tokens: int = 64, temperature: float = 0.0,
                         eos_token: Optional[int] = None) -> str:
        """Decode-side half of PD disaggregation: admit a request whose
        prompt KV was computed by a prefill replica. k/v are
        (layers, len(prompt), kv_heads, head_dim) arrays, logits the last
        prompt position's logits."""
        import uuid

        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + max_tokens > self.max_seq:
            raise ValueError("prompt + max_tokens exceeds max_seq")
        k, v = np.asarray(k), np.asarray(v)
        c = self.config
        want = (c.n_layers, len(prompt), c.n_kv_heads, c.head_dim)
        if k.shape != want or v.shape != want:
            # caller thread: surface the mismatch to the submitter rather
            # than blowing up the engine loop for every in-flight request
            raise ValueError(
                f"prefilled KV shape {k.shape}/{v.shape} != expected {want}")
        req = _Request(list(prompt), max_tokens, temperature, eos_token,
                       preload={"k": k, "v": v,
                                "logits": np.asarray(logits)})
        rid = uuid.uuid4().hex
        with self._pending_lock:
            self._pending[rid] = {"req": req, "sent": 0}
        self._queue.put(req)
        return rid

    def poll(self, request_id: str) -> Dict[str, Any]:
        """New tokens since the last poll + done flag. The entry is dropped
        once fully drained after completion."""
        with self._pending_lock:
            ent = self._pending.get(request_id)
            if ent is None:
                return {"chunks": [], "done": True}
            ent["last_poll"] = time.monotonic()
            req = ent["req"]
            out = list(req.output)   # snapshot (engine thread appends)
            chunks = out[ent["sent"]:]
            ent["sent"] = len(out)
            finished = req.done.is_set() and ent["sent"] >= len(req.output)
            if finished:
                del self._pending[request_id]
            if req.error:
                raise RuntimeError(req.error)
            return {"chunks": chunks, "done": finished}

    def stats(self) -> Dict[str, Any]:
        return {"steps": self._steps,
                "tokens_generated": self._tokens_generated,
                "active_slots": sum(s is not None for s in self._slots),
                "queued": self._queue.qsize(),
                "prefix_hits": self._prefix_hits,
                "prefix_misses": self._prefix_misses}

    def shutdown(self):
        self._stop.set()
        self._thread.join(timeout=5)

    # ------------------------------------------------------------- engine
    def _inject_kv(self, slot: int, k: np.ndarray, v: np.ndarray,
                   true_len: int):
        """Pad external KV rows to a bucket and write them into `slot`."""
        import jax.numpy as jnp

        from ray_tpu.models.decoding import pad_to_bucket

        P = min(pad_to_bucket(true_len), self.max_seq)
        pad = P - k.shape[1]
        if pad > 0:
            widths = ((0, 0), (0, pad), (0, 0), (0, 0))
            k = np.pad(k, widths)
            v = np.pad(v, widths)
        self._cache = self._inject(self._cache, jnp.asarray(k),
                                   jnp.asarray(v), true_len, slot)

    def _extract_kv(self, slot: int, true_len: int):
        """Device→host copy of one slot's prompt KV (rows [0, true_len))."""
        import jax

        k, v = jax.device_get((self._cache["k"][:, slot, :true_len],
                               self._cache["v"][:, slot, :true_len]))
        return np.asarray(k), np.asarray(v)

    def _admit(self):
        import jax.numpy as jnp

        from ray_tpu.models.decoding import pad_to_bucket

        for slot in range(self.num_slots):
            if self._slots[slot] is not None:
                continue
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            plen = len(req.prompt)
            key = tuple(req.prompt)
            cached = None if req.preload else self._prefix_cache.get(key)
            if req.preload is not None:
                # PD handoff: prompt KV computed by a prefill replica
                self._inject_kv(slot, req.preload["k"], req.preload["v"],
                                plen)
                logits_np = req.preload["logits"]
                req.preload = None  # free the host copy
            elif cached is not None:
                self._prefix_hits += 1
                self._prefix_cache.move_to_end(key)
                self._inject_kv(slot, cached["k"], cached["v"], plen)
                logits_np = cached["logits"]
            else:
                # cap padding at max_seq: a prompt that fits must be admitted
                P = min(pad_to_bucket(plen), self.max_seq)
                tokens = np.zeros((1, P), np.int32)
                tokens[0, :plen] = req.prompt
                self._cache, logits = self._prefill(
                    self._cache, jnp.asarray(tokens), plen, slot)
                logits_np = np.asarray(logits)
                if self._prefix_cache_size > 0:
                    self._prefix_misses += 1
                    k, v = self._extract_kv(slot, plen)
                    self._prefix_cache[key] = {"k": k, "v": v,
                                               "logits": logits_np}
                    while len(self._prefix_cache) > self._prefix_cache_size:
                        self._prefix_cache.popitem(last=False)
            tok = self._sample(logits_np.reshape(1, -1), req.temperature)[0]
            req.output.append(int(tok))
            self._slots[slot] = req
            self._last_token[slot] = tok
            self._maybe_finish(slot)

    def _sample(self, logits: np.ndarray, temperature: float) -> np.ndarray:
        if temperature <= 0.0:
            return logits.argmax(-1).astype(np.int32)
        z = logits / max(temperature, 1e-5)
        z = z - z.max(-1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(-1, keepdims=True)
        rng = np.random.default_rng(self._steps)
        return np.array([rng.choice(p.shape[-1], p=row) for row in p],
                        np.int32)

    def _maybe_finish(self, slot: int):
        req = self._slots[slot]
        if req is None:
            return
        done = (len(req.output) >= req.max_tokens
                or (req.eos_token is not None and req.output
                    and req.output[-1] == req.eos_token)
                or len(req.prompt) + len(req.output) >= self.max_seq)
        if done:
            req.done.set()
            self._slots[slot] = None

    def _loop(self):
        import logging
        import traceback

        while not self._stop.is_set():
            try:
                self._loop_once()
            except Exception as e:  # noqa: BLE001 — engine must survive
                logging.getLogger(__name__).error(
                    "engine step failed:\n%s", traceback.format_exc())
                # fail every active request rather than hanging them
                for slot in range(self.num_slots):
                    req = self._slots[slot]
                    if req is not None:
                        req.error = f"engine step failed: {e!r}"
                        req.done.set()
                        self._slots[slot] = None

    _PENDING_TTL_S = 180.0

    def _sweep_pending(self):
        """Drop submit/poll entries whose client stopped polling (stream
        abandoned mid-generation) so replicas don't leak per-request state."""
        now = time.monotonic()
        with self._pending_lock:
            stale = [rid for rid, ent in self._pending.items()
                     if now - ent.get("last_poll",
                                      ent["req"].enqueued_at)
                     > self._PENDING_TTL_S]
            for rid in stale:
                del self._pending[rid]

    def _loop_once(self):
        import jax.numpy as jnp

        self._steps_since_sweep = getattr(self, "_steps_since_sweep", 0) + 1
        if self._steps_since_sweep >= 500:
            self._steps_since_sweep = 0
            self._sweep_pending()
        self._admit()
        active = np.array([s is not None for s in self._slots])
        if not active.any():
            time.sleep(0.002)
            return
        self._cache, logits = self._decode(
            self._cache, jnp.asarray(self._last_token),
            jnp.asarray(active))
        logits_np = np.asarray(logits)
        self._steps += 1
        for slot in range(self.num_slots):
            req = self._slots[slot]
            if req is None:
                continue
            tok = self._sample(logits_np[slot][None], req.temperature)[0]
            req.output.append(int(tok))
            self._last_token[slot] = tok
            self._tokens_generated += 1
            self._maybe_finish(slot)


class LLMServer:
    """Serve deployment wrapper: one engine per replica.

    Deploy with ``serve.deployment(LLMServer).options(
    ray_actor_options={"num_tpus": N})``; requests are token-id lists
    (tokenization is a host-side pre/post step, kept off the replica).
    """

    def __init__(self, model: str = "tiny", num_slots: int = 8,
                 max_seq: Optional[int] = None, **engine_kwargs):
        self.engine = LLMEngine(model=model, num_slots=num_slots,
                                max_seq=max_seq, **engine_kwargs)

    @staticmethod
    def _parse(prompt_or_request, kwargs: Dict[str, Any]):
        """Accept either direct args (handle calls) or a proxy Request whose
        JSON body is {"prompt": [...], "max_tokens": n, ...}."""
        from ray_tpu.serve.proxy import Request

        if isinstance(prompt_or_request, Request):
            body = prompt_or_request.json() or {}
            merged = {"max_tokens": body.get("max_tokens", 64),
                      "temperature": body.get("temperature", 0.0),
                      "eos_token": body.get("eos_token")}
            return body.get("prompt", []), merged
        return prompt_or_request, kwargs

    def __call__(self, prompt_or_request, **kwargs) -> List[int]:
        prompt, kw = self._parse(prompt_or_request, kwargs)
        return self.engine.generate(
            prompt, kw.get("max_tokens", 64), kw.get("temperature", 0.0),
            kw.get("eos_token"))

    def submit(self, prompt_or_request, **kwargs) -> str:
        prompt, kw = self._parse(prompt_or_request, kwargs)
        return self.engine.submit(
            prompt, kw.get("max_tokens", 64), kw.get("temperature", 0.0),
            kw.get("eos_token"))

    def poll(self, request_id: str) -> Dict[str, Any]:
        return self.engine.poll(request_id)

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()
