"""DeploymentHandle: client-side router.

Reference: ``python/ray/serve/handle.py:639`` (DeploymentHandle,
``.remote():715``) + ``request_router/`` power-of-two-choices. The handle
caches the replica set (version-stamped from the controller), picks the
less-loaded of two random replicas by local outstanding counts, and
returns an ObjectRef. ``options()`` clones share one router state so load
accounting stays consistent across method handles.
"""

from __future__ import annotations

import asyncio
import collections
import random
import threading
import time
from typing import Any, Dict, List


class _SharedDecay:
    """ONE process-wide timer thread for load-count decay.

    The out-of-worker fallback in :meth:`DeploymentHandle._attach_completion`
    used to spawn a ``threading.Timer`` per call — a churny client outside
    any CoreWorker leaked a thread per request.  All decays share a fixed
    delay, so a single daemon thread draining a FIFO of
    ``(deadline, callback)`` covers every handle in the process."""

    _instance: "_SharedDecay" = None
    _instance_lock = threading.Lock()

    def __init__(self, delay_s: float = 1.0):
        self.delay_s = delay_s
        self._items: "collections.deque" = collections.deque()
        self._cv = threading.Condition()
        self._thread: threading.Thread = None

    @classmethod
    def instance(cls) -> "_SharedDecay":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def schedule(self, callback) -> None:
        with self._cv:
            self._items.append((time.monotonic() + self.delay_s, callback))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="serve-handle-decay")
                self._thread.start()
            self._cv.notify()

    def pending(self) -> int:
        with self._cv:
            return len(self._items)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._items:
                    self._cv.wait()
                deadline, cb = self._items[0]
                delay = deadline - time.monotonic()
                if delay > 0:
                    self._cv.wait(delay)
                    continue
                self._items.popleft()
            try:
                cb()
            except Exception:  # noqa: BLE001 — observer errors stay local
                pass


class _RouterState:
    """Replica set + outstanding counts, shared by all handle clones."""

    # prefix-affinity table bounds (prefix_aware router)
    PREFIX_CHUNK = 16
    PREFIX_MAX_CHUNKS = 8
    PREFIX_TABLE_CAP = 4096

    def __init__(self, deployment_name: str, controller):
        self.name = deployment_name
        self.controller = controller
        self.lock = threading.Lock()
        self.version = -1
        self.replicas: List[Any] = []
        self.outstanding: Dict[int, int] = {}
        self.max_ongoing = 8
        self.router = "pow2"
        self.last_refresh = 0.0
        import collections

        # cumulative-prefix hash -> replica index that last served it
        self._prefix_owner: "collections.OrderedDict" = \
            collections.OrderedDict()
        # replica index -> frozenset of prefix hashes the replica's
        # engine ADVERTISES as cached (radix-tree digest fetched through
        # the controller on refresh).  Second-tier routing signal: the
        # owner table knows what this handle sent; the digest knows what
        # the replica actually holds — including prefixes warmed by
        # OTHER handles/proxies.
        self.replica_digests: Dict[int, frozenset] = {}
        # multiplexed model id -> replica index that last loaded it
        # (reference: multiplexed model routing in request_router/)
        self._model_owner: "collections.OrderedDict" = \
            collections.OrderedDict()

    REFRESH_INTERVAL_S = 1.0

    def _is_fresh(self) -> bool:
        with self.lock:
            return bool(time.monotonic() - self.last_refresh
                        < self.REFRESH_INTERVAL_S and self.replicas)

    def _apply_refresh(self, version, replicas, max_ongoing, router) -> None:
        with self.lock:
            if version != self.version:
                self.version = version
                self.replicas = replicas
                self.outstanding = {i: 0 for i in range(len(replicas))}
                self._prefix_owner.clear()  # indices changed meaning
                self._model_owner.clear()
                self.replica_digests = {}
            self.max_ongoing = max_ongoing
            self.router = router
            self.last_refresh = time.monotonic()

    def _apply_digests(self, digests) -> None:
        with self.lock:
            self.replica_digests = {
                int(i): frozenset(int(h) for h in d)
                for i, d in dict(digests).items()}

    def refresh(self, force: bool = False):
        import ray_tpu

        if not force and self._is_fresh():
            return
        version, replicas, max_ongoing, router = ray_tpu.get(
            [self.controller.get_replicas.remote(self.name)], timeout=30.0)[0]
        self._apply_refresh(version, replicas, max_ongoing, router)
        if router == "prefix_aware":
            # What each replica's engine actually caches (vs the local
            # owner table's what-I-sent view).  Best-effort: a missed
            # fetch only costs routing quality, never availability.
            try:
                self._apply_digests(ray_tpu.get(
                    [self.controller.get_prefix_digests.remote(self.name)],
                    timeout=5.0)[0])
            except Exception:  # noqa: BLE001 — hint only
                pass

    async def refresh_async(self, force: bool = False):
        """Loop-native refresh: awaits the controller reply instead of
        parking a thread in a blocking ``get`` (the proxy's dispatch path
        must never block its event loop — rt-analyze loop-blocker gate)."""
        import ray_tpu

        if not force and self._is_fresh():
            return
        version, replicas, max_ongoing, router = await ray_tpu.get_async(
            self.controller.get_replicas.remote(self.name), timeout=30.0)
        self._apply_refresh(version, replicas, max_ongoing, router)
        if router == "prefix_aware":
            try:
                self._apply_digests(await ray_tpu.get_async(
                    self.controller.get_prefix_digests.remote(self.name),
                    timeout=5.0))
            except Exception:  # noqa: BLE001 — hint only
                pass

    @classmethod
    def _prefix_hashes(cls, key) -> List[int]:
        """Hashes of the cumulative CHUNK-sized prefixes of the routing
        key (tokens for list/tuple prompts, bytes for str/bytes),
        longest first."""
        import hashlib

        def h64(b: bytes) -> int:
            return int.from_bytes(
                hashlib.blake2b(b, digest_size=8).digest(), "little")

        hashes = []
        for n_chunks in range(cls.PREFIX_MAX_CHUNKS, 0, -1):
            cut = key[:n_chunks * cls.PREFIX_CHUNK]
            if not len(cut):
                continue
            if isinstance(cut, str):
                b = cut.encode()
            elif isinstance(cut, bytes):
                b = cut
            else:
                b = repr(tuple(cut)).encode()
            h = h64(b)
            if not hashes or hashes[-1] != h:
                hashes.append(h)
        return hashes

    def _pick_pow2(self) -> int:
        n = len(self.replicas)
        if n == 1:
            return 0
        a, b = random.sample(range(n), 2)
        return a if self.outstanding.get(a, 0) <= \
            self.outstanding.get(b, 0) else b

    MODEL_TABLE_CAP = 1024

    def acquire_replica(self, routing_key=None, model_id=None,
                        count: int = 1):
        """Pick + increment (by ``count`` — a coalesced batch of N
        requests loads its replica as N) under ONE lock hold; returns
        (replica, index) or None if no replicas.

        pow2: less-loaded of two random replicas. prefix_aware
        (reference: serve request_router/ prefix-aware over vLLM prefix
        caching): the replica that last served the longest matching
        request prefix, so its engine prefix cache hits — unless it is
        saturated, then fall back to pow2 and adopt the new owner.
        A multiplexed ``model_id`` (any router mode) takes precedence:
        route to the replica that last loaded the model so its LRU cache
        hits — loading is the expensive HBM-staging step."""
        with self.lock:
            n = len(self.replicas)
            if n == 0:
                return None
            idx = None
            hashes = []
            if model_id is not None:
                owner = self._model_owner.get(model_id)
                if owner is not None and owner < n and \
                        self.outstanding.get(owner, 0) < self.max_ongoing:
                    idx = owner
            if idx is None and self.router == "prefix_aware" \
                    and routing_key is not None:
                hashes = self._prefix_hashes(routing_key)
                for h in hashes:  # longest cumulative prefix first
                    owner = self._prefix_owner.get(h)
                    if owner is not None and owner < n and \
                            self.outstanding.get(owner, 0) < self.max_ongoing:
                        idx = owner
                        break
                if idx is None and self.replica_digests:
                    # owner table missed — consult the replicas' own
                    # advertisements (prefixes warmed through other
                    # handles still route hot)
                    for h in hashes:
                        for cand, dig in self.replica_digests.items():
                            if h in dig and cand < n and \
                                    self.outstanding.get(cand, 0) \
                                    < self.max_ongoing:
                                idx = cand
                                break
                        if idx is not None:
                            break
            if idx is None:
                idx = self._pick_pow2()
            for h in hashes:  # adopt/refresh ownership
                self._prefix_owner[h] = idx
                self._prefix_owner.move_to_end(h)
            while len(self._prefix_owner) > self.PREFIX_TABLE_CAP:
                self._prefix_owner.popitem(last=False)
            if model_id is not None:
                self._model_owner[model_id] = idx
                self._model_owner.move_to_end(model_id)
                while len(self._model_owner) > self.MODEL_TABLE_CAP:
                    self._model_owner.popitem(last=False)
            self.outstanding[idx] = self.outstanding.get(idx, 0) + count
            return self.replicas[idx], idx

    def release(self, idx: int, count: int = 1):
        with self.lock:
            self.outstanding[idx] = max(
                0, self.outstanding.get(idx, count) - count)

    def mark_dead(self, actor_id) -> None:
        """Router-local health view: drop a replica the data plane just
        watched die, WITHOUT waiting for the controller's health probes to
        notice.  The controller's own ejection bumps the replica-set
        version, so the next refresh re-syncs; until then this keeps
        retries off the corpse.  (``_apply_refresh`` only rewrites the
        set on a version change, so the local removal is not resurrected
        by a same-version refresh.)"""
        try:
            dead_hex = actor_id.hex()
        except AttributeError:
            dead_hex = str(actor_id)
        with self.lock:
            keep = [r for r in self.replicas
                    if r._actor_id.hex() != dead_hex]
            if len(keep) == len(self.replicas):
                return
            self.replicas = keep
            # indices changed meaning: reset load + affinity tables (the
            # blip in load accounting is noise next to a replica death)
            self.outstanding = {i: 0 for i in range(len(keep))}
            self._prefix_owner.clear()
            self._model_owner.clear()
            self.replica_digests = {}


def _rebuild_handle(name, controller, method, model_id=None):
    return DeploymentHandle(name, controller, _method=method,
                            _model_id=model_id)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 _state: _RouterState = None, _method: str = "__call__",
                 _model_id: str = None):
        self._state = _state or _RouterState(deployment_name, controller)
        self._method = _method
        self._model_id = _model_id

    def __reduce__(self):
        # handles cross process boundaries (e.g. composed deployments
        # receive downstream handles as init args — reference pattern);
        # the router state rebuilds fresh on the receiving side
        return (_rebuild_handle,
                (self._state.name, self._state.controller, self._method,
                 self._model_id))

    @property
    def _name(self):
        return self._state.name

    def options(self, method_name: str = None,
                multiplexed_model_id: str = None) -> "DeploymentHandle":
        """Clone sharing router state. ``multiplexed_model_id`` tags
        requests for a ``@serve.multiplexed`` deployment (reference:
        ``handle.options(multiplexed_model_id=...)``)."""
        return DeploymentHandle(
            self._state.name, self._state.controller, _state=self._state,
            _method=method_name if method_name is not None else self._method,
            _model_id=(multiplexed_model_id if multiplexed_model_id
                       is not None else self._model_id))

    ACQUIRE_TIMEOUT_S = 30.0

    def _routing_key(self, args):
        # prefix_aware routing keys off the first positional argument of
        # REQUEST-carrying methods only (the prompt for LLM deployments);
        # bookkeeping methods like poll(request_id) must not churn the
        # affinity table or be routed by a meaningless key
        if self._method in ("__call__", "generate", "submit") and args \
                and isinstance(args[0], (str, bytes, list, tuple)):
            return args[0]
        return None

    def _submit_to(self, acquired, args, kwargs):
        replica, idx = acquired
        try:
            ref = replica.handle_request.remote(self._method, args, kwargs)
        except BaseException:
            self._state.release(idx)
            raise
        self._attach_completion(ref, idx)
        return ref

    def remote(self, *args, **kwargs):
        deadline = time.monotonic() + self.ACQUIRE_TIMEOUT_S
        routing_key = self._routing_key(args)
        if self._model_id is not None:
            kwargs = dict(kwargs)
            kwargs["_multiplexed_model_id"] = self._model_id
        acquired = None
        while acquired is None:
            self._state.refresh()
            acquired = self._state.acquire_replica(routing_key,
                                                   self._model_id)
            if acquired is None:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"deployment {self._name!r} has no running replicas")
                time.sleep(0.1)
                self._state.refresh(force=True)
        return self._submit_to(acquired, args, kwargs)

    async def _acquire_async(self, routing_key=None, model_id=None,
                             count: int = 1):
        """Loop-native acquire-with-retry (ONE copy for both async
        dispatch flavors): every wait is an ``await`` — no ``time.sleep``,
        no blocking controller ``get``."""
        deadline = time.monotonic() + self.ACQUIRE_TIMEOUT_S
        while True:
            await self._state.refresh_async()
            acquired = self._state.acquire_replica(routing_key, model_id,
                                                   count)
            if acquired is not None:
                return acquired
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"deployment {self._name!r} has no running replicas")
            await asyncio.sleep(0.1)
            await self._state.refresh_async(force=True)

    async def remote_async(self, *args, **kwargs):
        """Async-native dispatch: same routing, acquire/retry, and load
        accounting as :meth:`remote`, runnable directly on a server's
        event loop (the proxy's per-request path)."""
        routing_key = self._routing_key(args)
        if self._model_id is not None:
            kwargs = dict(kwargs)
            kwargs["_multiplexed_model_id"] = self._model_id
        acquired = await self._acquire_async(routing_key, self._model_id)
        return self._submit_to(acquired, args, kwargs)

    async def remote_batch_async(self, calls):
        """Coalesced dispatch of ``calls`` — a list of ``(args, kwargs)``
        pairs — as ONE ``handle_request_batch`` actor call to ONE replica
        (round 11 proxy micro-batching).  Load accounting weights the
        replica by ``len(calls)``; per-item failures come back as
        ``_ItemError`` entries in the result list, not exceptions."""
        count = len(calls)
        replica, idx = await self._acquire_async(count=count)
        try:
            ref = replica.handle_request_batch.remote(self._method, calls)
        except BaseException:
            self._state.release(idx, count)
            raise
        self._attach_completion(ref, idx, count)
        return ref

    def _attach_completion(self, ref, idx: int, count: int = 1):
        """Decrement the outstanding count when the reply lands."""
        state = self._state

        def done():
            state.release(idx, count)

        try:
            from ray_tpu.core_worker.worker import CoreWorker

            cw = CoreWorker.current_or_raise()
            cw.memory_store.add_done_callback(ref.object_id, done)
        except Exception:  # noqa: BLE001 — degrade to time-based decay
            # on the ONE shared timer thread (never a Timer per call: a
            # churny out-of-worker client would leak a thread per request)
            _SharedDecay.instance().schedule(done)
