"""DeploymentHandle: client-side router.

Reference: ``python/ray/serve/handle.py:639`` (DeploymentHandle,
``.remote():715``) + ``request_router/`` power-of-two-choices. The handle
caches the replica set (version-stamped from the controller), picks the
less-loaded of two random replicas by local outstanding counts, and
returns an ObjectRef. ``options()`` clones share one router state so load
accounting stays consistent across method handles.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List


class _RouterState:
    """Replica set + outstanding counts, shared by all handle clones."""

    def __init__(self, deployment_name: str, controller):
        self.name = deployment_name
        self.controller = controller
        self.lock = threading.Lock()
        self.version = -1
        self.replicas: List[Any] = []
        self.outstanding: Dict[int, int] = {}
        self.max_ongoing = 8
        self.last_refresh = 0.0

    REFRESH_INTERVAL_S = 1.0

    def refresh(self, force: bool = False):
        import ray_tpu

        now = time.monotonic()
        with self.lock:
            fresh = (now - self.last_refresh < self.REFRESH_INTERVAL_S
                     and self.replicas)
        if not force and fresh:
            return
        version, replicas, max_ongoing = ray_tpu.get(
            [self.controller.get_replicas.remote(self.name)], timeout=30.0)[0]
        with self.lock:
            if version != self.version:
                self.version = version
                self.replicas = replicas
                self.outstanding = {i: 0 for i in range(len(replicas))}
            self.max_ongoing = max_ongoing
            self.last_refresh = now

    def acquire_replica(self):
        """Pick (power-of-two-choices) + increment under ONE lock hold;
        returns (replica, index) or None if no replicas."""
        with self.lock:
            n = len(self.replicas)
            if n == 0:
                return None
            if n == 1:
                idx = 0
            else:
                a, b = random.sample(range(n), 2)
                idx = a if self.outstanding.get(a, 0) <= \
                    self.outstanding.get(b, 0) else b
            self.outstanding[idx] = self.outstanding.get(idx, 0) + 1
            return self.replicas[idx], idx

    def release(self, idx: int):
        with self.lock:
            self.outstanding[idx] = max(0, self.outstanding.get(idx, 1) - 1)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller,
                 _state: _RouterState = None, _method: str = "__call__"):
        self._state = _state or _RouterState(deployment_name, controller)
        self._method = _method

    @property
    def _name(self):
        return self._state.name

    def options(self, method_name: str = "__call__") -> "DeploymentHandle":
        return DeploymentHandle(self._state.name, self._state.controller,
                                _state=self._state, _method=method_name)

    def remote(self, *args, **kwargs):
        deadline = time.monotonic() + 30.0
        acquired = None
        while acquired is None:
            self._state.refresh()
            acquired = self._state.acquire_replica()
            if acquired is None:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"deployment {self._name!r} has no running replicas")
                time.sleep(0.1)
                self._state.refresh(force=True)
        replica, idx = acquired
        try:
            ref = replica.handle_request.remote(self._method, args, kwargs)
        except BaseException:
            self._state.release(idx)
            raise
        self._attach_completion(ref, idx)
        return ref

    def _attach_completion(self, ref, idx: int):
        """Decrement the outstanding count when the reply lands."""
        state = self._state

        def done():
            state.release(idx)

        try:
            from ray_tpu.core_worker.worker import CoreWorker

            cw = CoreWorker.current_or_raise()
            cw.memory_store.add_done_callback(ref.object_id, done)
        except Exception:  # noqa: BLE001 — degrade to time-based decay
            threading.Timer(1.0, done).start()
