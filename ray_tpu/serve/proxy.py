"""Serve ingress proxies: HTTP and gRPC.

Reference: ``python/ray/serve/_private/proxy.py`` (``HTTPProxy:696`` ASGI,
``gRPCProxy:520``, ``ProxyActor:1008``) with route-table push via long-poll
(``long_poll.py``). Here the proxy is an async actor:

- HTTP/1.1 server on asyncio streams (no external web framework): requests
  are parsed into a picklable :class:`Request`, routed by longest matching
  route prefix to a :class:`DeploymentHandle`, and the replica's return
  value is rendered (str/bytes/dict/Response). ``Accept: text/event-stream``
  switches to the submit/poll streaming protocol (SSE) for deployments that
  implement it (e.g. the LLM server streams tokens).
- gRPC server (grpc.aio, generic handler — no compiled protos): unary call
  to ``/<app>/<method>`` with a pickled ``(args, kwargs)`` payload, reply is
  the pickled return value.
- The route table is version-stamped; the proxy long-polls the controller
  (``listen_for_route_table``) so redeploys propagate promptly without a
  hot refresh loop.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import pickle
import time
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

PROXY_NAME = "SERVE_PROXY"


@dataclasses.dataclass
class Request:
    """Picklable HTTP request passed to deployment callables."""

    method: str
    path: str
    query: Dict[str, str]
    headers: Dict[str, str]
    body: bytes

    def json(self) -> Any:
        return json.loads(self.body or b"null")

    @property
    def text(self) -> str:
        return self.body.decode("utf-8", "replace")


@dataclasses.dataclass
class Response:
    """Explicit response: deployments may return one for full control."""

    body: Any = b""
    status: int = 200
    content_type: str = "application/octet-stream"
    headers: Optional[Dict[str, str]] = None


def _render(result: Any) -> Tuple[int, str, bytes, Dict[str, str]]:
    """Map a deployment return value onto (status, content-type, body)."""
    if isinstance(result, Response):
        body = result.body
        if isinstance(body, str):
            body = body.encode()
        elif not isinstance(body, (bytes, bytearray)):
            body = json.dumps(body).encode()
        return (result.status, result.content_type, bytes(body),
                result.headers or {})
    if isinstance(result, (bytes, bytearray)):
        return 200, "application/octet-stream", bytes(result), {}
    if isinstance(result, str):
        return 200, "text/plain; charset=utf-8", result.encode(), {}
    return 200, "application/json", json.dumps(result).encode(), {}


class ProxyActor:
    """Ingress actor: one per cluster by default (reference ProxyActor)."""

    def __init__(self, http_host: str = "127.0.0.1", http_port: int = 0,
                 grpc_port: Optional[int] = None):
        self._http_host = http_host
        self._http_port = http_port
        self._grpc_port = grpc_port
        self._routes: Dict[str, Any] = {}       # route_prefix -> handle
        self._route_version = -1
        self._server: Optional[asyncio.AbstractServer] = None
        self._grpc_server = None
        self._pool = ThreadPoolExecutor(max_workers=32,
                                        thread_name_prefix="proxy")
        self._started = asyncio.Event()
        self._starting = False
        self._num_requests = 0

    # -------------------------------------------------------------- control
    async def start(self) -> Dict[str, Any]:
        """Bind servers; returns the bound addresses. Idempotent: a second
        caller racing the first gets the already-bound address."""
        if self._server is not None or self._starting:
            await self._started.wait()
            return self.address()
        self._starting = True  # set before ANY await: guards double-bind
        try:
            self._server = await asyncio.start_server(
                self._handle_conn, self._http_host, self._http_port)
            self._http_port = self._server.sockets[0].getsockname()[1]
            await self._refresh_routes()
            if self._grpc_port is not None:
                await self._start_grpc()
        except BaseException:
            # a failed bind must not wedge every future start() behind
            # an event that will never be set
            self._starting = False
            if self._server is not None:
                self._server.close()
                self._server = None
            raise
        asyncio.get_running_loop().create_task(self._route_poll_loop())
        self._started.set()
        logger.info("serve proxy: http on %s:%d grpc on %s",
                    self._http_host, self._http_port, self._grpc_port)
        return {"http_host": self._http_host, "http_port": self._http_port,
                "grpc_port": self._grpc_port}

    def address(self) -> Dict[str, Any]:
        return {"http_host": self._http_host, "http_port": self._http_port,
                "grpc_port": self._grpc_port}

    def num_requests(self) -> int:
        return self._num_requests

    async def stop(self) -> bool:
        if self._server is not None:
            self._server.close()
        if self._grpc_server is not None:
            await self._grpc_server.stop(grace=1.0)
        return True

    # ---------------------------------------------------------- route table
    def _controller(self):
        from ray_tpu.serve.api import _get_or_create_controller

        return _get_or_create_controller()

    async def _refresh_routes(self):
        import ray_tpu
        from ray_tpu.serve.handle import DeploymentHandle

        loop = asyncio.get_running_loop()
        controller = self._controller()

        def fetch():
            return ray_tpu.get(
                [controller.get_route_table.remote()], timeout=30.0)[0]

        version, table = await loop.run_in_executor(self._pool, fetch)
        if version != self._route_version:
            self._routes = {
                prefix: DeploymentHandle(app_name, controller)
                for prefix, app_name in table.items()}
            self._route_version = version

    async def _route_poll_loop(self):
        """Long-poll the controller: returns promptly on version change,
        every ~15 s otherwise (reference long_poll.py)."""
        import ray_tpu

        loop = asyncio.get_running_loop()
        controller = self._controller()
        while self._server is not None and self._server.is_serving():
            try:
                version = self._route_version

                def wait():
                    return ray_tpu.get(
                        [controller.listen_for_route_table.remote(version)],
                        timeout=60.0)[0]

                await loop.run_in_executor(self._pool, wait)
                await self._refresh_routes()
            except Exception:  # noqa: BLE001 — controller restarting
                await asyncio.sleep(1.0)

    def _match_route(self, path: str):
        """Longest-prefix route match (reference route longest-prefix)."""
        best = None
        for prefix, handle in self._routes.items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(norm + "/") or norm == "/":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, handle)
        return best

    # ------------------------------------------------------------- http
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    return
                try:
                    method, target, _version = line.decode().split(" ", 2)
                except ValueError:
                    await self._write_simple(writer, 400, b"bad request line")
                    return
                headers: Dict[str, str] = {}
                while True:
                    hline = await reader.readline()
                    if hline in (b"\r\n", b"\n", b""):
                        break
                    name, _, value = hline.decode().partition(":")
                    headers[name.strip().lower()] = value.strip()
                length = int(headers.get("content-length", "0") or "0")
                body = await reader.readexactly(length) if length else b""
                parsed = urllib.parse.urlsplit(target)
                query = dict(urllib.parse.parse_qsl(parsed.query))
                req = Request(method=method.upper(), path=parsed.path,
                              query=query, headers=headers, body=body)
                keep_alive = headers.get("connection", "").lower() != "close"
                await self._dispatch(req, writer)
                if not keep_alive:
                    return
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _dispatch(self, req: Request, writer: asyncio.StreamWriter):
        self._num_requests += 1
        if req.path == "/-/routes":  # reference exposes the route table
            table = {p: h._name for p, h in self._routes.items()}
            await self._write_response(
                writer, 200, "application/json", json.dumps(table).encode())
            return
        if req.path == "/-/healthz":
            await self._write_response(writer, 200, "text/plain", b"ok")
            return
        match = self._match_route(req.path)
        if match is None:
            await self._refresh_routes()
            match = self._match_route(req.path)
        if match is None:
            await self._write_simple(writer, 404, b"no matching route")
            return
        prefix, handle = match
        if req.headers.get("accept") == "text/event-stream":
            await self._dispatch_stream(req, handle, writer)
            return
        loop = asyncio.get_running_loop()

        def call():
            import ray_tpu
            from ray_tpu.common.status import ActorDiedError

            # A replica can die between routing and execution (downscale
            # drain timeout, crash): retry on a fresh replica like the
            # reference router does before surfacing an error.
            for attempt in range(3):
                ref = handle.remote(req)
                try:
                    return ray_tpu.get(ref, timeout=120.0)
                except ActorDiedError:
                    if attempt == 2:
                        raise
                    handle._state.refresh(force=True)

        try:
            result = await loop.run_in_executor(self._pool, call)
        except Exception as e:  # noqa: BLE001 — replica/user error → 500
            await self._write_response(
                writer, 500, "text/plain",
                f"deployment error: {e}".encode()[:4096])
            return
        status, ctype, body, extra = _render(result)
        await self._write_response(writer, status, ctype, body, extra)

    async def _dispatch_stream(self, req: Request, handle,
                               writer: asyncio.StreamWriter):
        """SSE streaming via the submit/poll protocol: the deployment
        implements ``submit(request) -> req_id`` and ``poll(req_id) ->
        {"chunks": [...], "done": bool}`` (the LLM server streams tokens
        this way)."""
        import ray_tpu

        loop = asyncio.get_running_loop()
        # Sticky routing: submit and every poll must hit the SAME replica
        # (the request id lives in that replica's engine state).
        handle._state.refresh()
        acquired = handle._state.acquire_replica()
        if acquired is None:
            await self._write_response(writer, 500, "text/plain",
                                       b"no running replicas")
            return
        replica, ridx = acquired
        try:
            use_gen = await loop.run_in_executor(
                self._pool, lambda: ray_tpu.get(
                    replica.supports_generator_stream.remote(),
                    timeout=30.0))
        except Exception:  # noqa: BLE001 — older replica: poll protocol
            use_gen = False
        if use_gen:
            # streaming-generator protocol: items PUSH from the replica
            # (num_returns="streaming" + owner backpressure), no poll RPCs
            try:
                await self._stream_via_generator(req, replica, writer)
            finally:
                handle._state.release(ridx)
            return
        try:
            req_id = await loop.run_in_executor(
                self._pool, lambda: ray_tpu.get(
                    replica.handle_request.remote("submit", (req,), {}),
                    timeout=60.0))
        except Exception as e:  # noqa: BLE001
            handle._state.release(ridx)
            await self._write_response(
                writer, 500, "text/plain",
                f"stream submit failed: {e}".encode()[:4096])
            return
        try:
            writer.write(b"HTTP/1.1 200 OK\r\n"
                         b"content-type: text/event-stream\r\n"
                         b"cache-control: no-cache\r\n"
                         b"transfer-encoding: chunked\r\n\r\n")
            await writer.drain()
            while True:
                out = await loop.run_in_executor(
                    self._pool, lambda: ray_tpu.get(
                        replica.handle_request.remote("poll", (req_id,), {}),
                        timeout=60.0))
                for chunk in out.get("chunks", ()):
                    payload = json.dumps(chunk).encode()
                    await self._write_chunk(
                        writer, b"data: " + payload + b"\n\n")
                if out.get("done"):
                    await self._write_chunk(writer, b"data: [DONE]\n\n")
                    break
                await asyncio.sleep(0.02)
        except (ConnectionError, OSError):
            return
        except Exception as e:  # noqa: BLE001
            try:
                await self._write_chunk(
                    writer, b"event: error\ndata: " + str(e).encode() + b"\n\n")
            except Exception:  # noqa: BLE001
                pass
        finally:
            handle._state.release(ridx)
        try:
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except Exception:  # noqa: BLE001
            pass

    async def _stream_via_generator(self, req, replica,
                                    writer: asyncio.StreamWriter):
        import ray_tpu

        loop = asyncio.get_running_loop()
        gen = replica.handle_request_stream.options(
            num_returns="streaming").remote((req,), {})
        try:
            writer.write(b"HTTP/1.1 200 OK\r\n"
                         b"content-type: text/event-stream\r\n"
                         b"cache-control: no-cache\r\n"
                         b"transfer-encoding: chunked\r\n\r\n")
            await writer.drain()
            async for ref in gen:
                chunk = await loop.run_in_executor(
                    self._pool, lambda r=ref: ray_tpu.get(r, timeout=60.0))
                payload = json.dumps(chunk).encode()
                await self._write_chunk(writer, b"data: " + payload + b"\n\n")
            await self._write_chunk(writer, b"data: [DONE]\n\n")
        except (ConnectionError, OSError):
            gen.close()  # consumer gone: cancel the stream at the replica
            return
        except Exception as e:  # noqa: BLE001
            try:
                await self._write_chunk(
                    writer,
                    b"event: error\ndata: " + str(e).encode() + b"\n\n")
            except Exception:  # noqa: BLE001
                pass
        try:
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except Exception:  # noqa: BLE001
            pass

    @staticmethod
    async def _write_chunk(writer: asyncio.StreamWriter, data: bytes):
        writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        await writer.drain()

    @staticmethod
    async def _write_response(writer: asyncio.StreamWriter, status: int,
                              ctype: str, body: bytes,
                              extra: Optional[Dict[str, str]] = None):
        reason = {200: "OK", 404: "Not Found", 400: "Bad Request",
                  500: "Internal Server Error"}.get(status, "OK")
        head = [f"HTTP/1.1 {status} {reason}",
                f"content-type: {ctype}",
                f"content-length: {len(body)}"]
        for k, v in (extra or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
        await writer.drain()

    @staticmethod
    async def _write_simple(writer, status: int, msg: bytes):
        await ProxyActor._write_response(writer, status, "text/plain", msg)

    # ------------------------------------------------------------- grpc
    async def _start_grpc(self):
        """Generic unary gRPC ingress: /<app>/<method>, pickled payloads
        (reference gRPCProxy:520 serves user protos; we stay proto-less)."""
        import grpc

        proxy = self

        class Generic(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                path = handler_call_details.method  # "/<app>/<method>"

                async def unary(request_bytes, context):
                    _, app, method = path.split("/", 2)
                    handle = None
                    for prefix, h in proxy._routes.items():
                        if h._name == app or prefix.strip("/") == app:
                            handle = h
                            break
                    if handle is None:
                        await proxy._refresh_routes()
                        for prefix, h in proxy._routes.items():
                            if h._name == app or prefix.strip("/") == app:
                                handle = h
                                break
                    if handle is None:
                        # outside any try: abort signals by raising and must
                        # not be re-wrapped as INTERNAL
                        await context.abort(grpc.StatusCode.NOT_FOUND,
                                            f"no deployment {app!r}")
                    try:
                        args, kwargs = pickle.loads(request_bytes) \
                            if request_bytes else ((), {})
                        loop = asyncio.get_running_loop()

                        def call():
                            import ray_tpu

                            ref = handle.options(method).remote(
                                *args, **kwargs)
                            return ray_tpu.get(ref, timeout=120.0)

                        result = await loop.run_in_executor(
                            proxy._pool, call)
                        return pickle.dumps(result)
                    except Exception as e:  # noqa: BLE001
                        await context.abort(grpc.StatusCode.INTERNAL, str(e))

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b)

        from grpc import aio as grpc_aio

        self._grpc_server = grpc_aio.server()
        self._grpc_server.add_generic_rpc_handlers((Generic(),))
        bound = self._grpc_server.add_insecure_port(
            f"{self._http_host}:{self._grpc_port or 0}")
        self._grpc_port = bound
        await self._grpc_server.start()
