"""Serve ingress proxies: HTTP and gRPC.

Reference: ``python/ray/serve/_private/proxy.py`` (``HTTPProxy:696`` ASGI,
``gRPCProxy:520``, ``ProxyActor:1008``) with route-table push via long-poll
(``long_poll.py``). Here the proxy is an async actor:

- HTTP/1.1 server on asyncio streams (no external web framework): requests
  are parsed into a picklable :class:`Request`, routed by a prebuilt
  longest-prefix matcher to a :class:`DeploymentHandle`, and the replica's
  return value is rendered (str/bytes/dict/Response).
  ``Accept: text/event-stream`` switches to SSE streaming.
- The data plane is ASYNC-NATIVE (round 11): dispatch awaits the replica
  reply on the proxy's own event loop via ``get_async`` — no thread-pool
  hop, no executor thread parked in a blocking ``get`` per request.  SSE
  rides the streaming-generator protocol push-first (items wake the loop
  directly; ``writer.drain`` backpressures a slow client through the
  owner-side generator backpressure to the replica), with the submit/poll
  protocol kept only as a fallback for pre-generator replicas.
- Per-stage latency accounting (route/queue/replica/render/write) feeds
  ``util/metrics`` histograms and the actor's ``debug_state()``; the
  ``executor_hops`` counter proves the hot path takes zero
  ``run_in_executor`` hops.
- gRPC server (grpc.aio, generic handler — no compiled protos): unary call
  to ``/<app>/<method>`` with a pickled ``(args, kwargs)`` payload, reply is
  the pickled return value.
- The route table is version-stamped; the proxy long-polls the controller
  (``listen_for_route_table``) so redeploys propagate promptly without a
  hot refresh loop.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import json
import logging
import math
import pickle
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

# hoisted off the per-request path: a `from ray_tpu.api import ...`
# inside the handler costs ~10µs of import machinery per call at proxy
# request rates (no cycle: ray_tpu.api never imports serve)
from ray_tpu.api import get_async
from ray_tpu.common import faults
from ray_tpu.common.status import ActorDiedError, TaskError
from ray_tpu.serve.controller import _ItemError

logger = logging.getLogger(__name__)

PROXY_NAME = "SERVE_PROXY"


@dataclasses.dataclass
class Request:
    """Picklable HTTP request passed to deployment callables."""

    method: str
    path: str
    query: Dict[str, str]
    headers: Dict[str, str]
    body: bytes

    def json(self) -> Any:
        return json.loads(self.body or b"null")

    @property
    def text(self) -> str:
        return self.body.decode("utf-8", "replace")


@dataclasses.dataclass
class Response:
    """Explicit response: deployments may return one for full control."""

    body: Any = b""
    status: int = 200
    content_type: str = "application/octet-stream"
    headers: Optional[Dict[str, str]] = None


class SSEBatch(list):
    """Several SSE data events in ONE streamed item.

    A deployment's ``stream`` generator may yield ``SSEBatch([...])`` to
    amortize the per-item report RPC of the streaming-generator protocol
    when it produces in bursts (the LLM engine emits every token decoded
    since the last poll): the proxy renders one ``data:`` event per
    element and ships them in a single coalesced write.  A plain ``list``
    yield stays ONE event whose payload is the list."""


def _render(result: Any) -> Tuple[int, str, bytes, Dict[str, str]]:
    """Map a deployment return value onto (status, content-type, body)."""
    if isinstance(result, Response):
        body = result.body
        if isinstance(body, str):
            body = body.encode()
        elif not isinstance(body, (bytes, bytearray)):
            body = json.dumps(body).encode()
        return (result.status, result.content_type, bytes(body),
                result.headers or {})
    if isinstance(result, (bytes, bytearray)):
        return 200, "application/octet-stream", bytes(result), {}
    if isinstance(result, str):
        return 200, "text/plain; charset=utf-8", result.encode(), {}
    return 200, "application/json", json.dumps(result).encode(), {}


# Request/Response cross the proxy→replica boundary on EVERY request;
# registering them as plain-safe keeps them on the C pickler (both
# classes are framework-owned, so pickle's by-reference class encoding is
# importable in every worker).  Unregistered, the serializer's whitelist
# walk fails on the dataclass and falls back to cloudpickle's
# Python-level pickler — measured ~70µs per request on the proxy loop.
def _register_plain_safe_types():
    from ray_tpu.core_worker import serialization as _ser

    _ser.register_plain_safe(
        Request, lambda v, budget: _ser._plain_safe(vars(v), budget=budget))
    _ser.register_plain_safe(
        Response, lambda v, budget: _ser._plain_safe(vars(v), budget=budget))
    _ser.register_plain_safe(
        SSEBatch, lambda v, budget: _ser._plain_safe(list(v), budget=budget))


_register_plain_safe_types()


class _BadRequest(Exception):
    """Parse-level rejection: (status, message) to answer before closing
    the connection — malformed bytes must produce a response, never an
    unhandled exception that kills the connection silently."""

    def __init__(self, status: int, message: bytes):
        self.status = status
        self.message = message
        super().__init__(message)


class _StageClock:
    """Per-request stage timer: ``lap(stage)`` records the time since the
    previous lap under that stage name."""

    __slots__ = ("stats", "t0", "last")

    def __init__(self, stats: "_StageStats"):
        self.stats = stats
        self.t0 = time.perf_counter()
        self.last = self.t0

    def lap(self, stage: str) -> None:
        now = time.perf_counter()
        self.stats.observe(stage, now - self.last)
        self.last = now

    def skip(self) -> None:
        """Reset the lap origin without recording (the elapsed span was
        accounted elsewhere, e.g. by the batcher's queue/replica laps)."""
        self.last = time.perf_counter()

    def finish(self) -> None:
        self.stats.observe("total", time.perf_counter() - self.t0)


class _StageStats:
    """Per-stage latency accounting for the request hot path.

    Feeds two sinks: the process metrics registry (``util/metrics``
    histogram ``rt_serve_stage_seconds`` + counters, scrapable via
    ``prometheus_text``/``collect_cluster_metrics``) and bounded local
    sample buffers that ``ProxyActor.debug_state`` turns into percentiles.
    ``executor_hops`` counts every ``run_in_executor`` hop the request
    path takes — the async-native contract is that it stays ZERO; tests
    assert on it."""

    STAGES = ("route", "queue", "replica", "render", "write", "total")

    def __init__(self):
        from ray_tpu.util.metrics import Counter, Histogram

        self._hist = Histogram(
            "rt_serve_stage_seconds",
            "per-stage proxy request latency",
            boundaries=[0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                        0.1, 0.25, 1.0, 5.0],
            tag_keys=("stage",))
        self._requests_total = Counter(
            "rt_serve_requests_total", "requests dispatched by the proxy")
        self._shed_counter = Counter(
            "rt_serve_shed_total",
            "requests shed by admission control before dispatch",
            tag_keys=("status",))
        self._hops_counter = Counter(
            "rt_serve_executor_hops_total",
            "run_in_executor hops taken on the proxy request path "
            "(async-native contract: zero)")
        self.requests = 0
        self.executor_hops = 0
        self.shed: Dict[int, int] = collections.Counter()
        self.stream_protocols: Dict[str, int] = collections.Counter()
        self.batch_sizes: Dict[int, int] = collections.Counter()
        self._samples: Dict[str, collections.deque] = {
            s: collections.deque(maxlen=4096) for s in self.STAGES}

    def clock(self) -> _StageClock:
        self.requests += 1
        self._requests_total.inc()
        return _StageClock(self)

    def observe(self, stage: str, elapsed: float) -> None:
        self._hist.observe(elapsed, tags={"stage": stage})
        buf = self._samples.get(stage)
        if buf is not None:
            buf.append(elapsed)

    def count_executor_hop(self) -> None:
        self.executor_hops += 1
        self._hops_counter.inc()

    def count_shed(self, status: int) -> None:
        self.shed[status] += 1
        self._shed_counter.inc(tags={"status": str(status)})

    def snapshot(self) -> Dict[str, Any]:
        stages = {}
        for stage, buf in self._samples.items():
            if not buf:
                continue
            ordered = sorted(buf)
            n = len(ordered)
            stages[stage] = {
                "count": n,
                "p50_ms": round(ordered[n // 2] * 1000, 3),
                "p99_ms": round(ordered[min(n - 1, (n * 99) // 100)]
                                * 1000, 3),
            }
        return {"requests": self.requests,
                "executor_hops": self.executor_hops,
                "shed": {str(k): v for k, v in sorted(self.shed.items())},
                "stream_protocols": dict(self.stream_protocols),
                "batch_sizes": {str(k): v
                                for k, v in sorted(self.batch_sizes.items())},
                "stages": stages}


class _RouteMatcher:
    """Prebuilt route table: exact-prefix dict hit first, then prefixes
    longest-first (built ONCE per route-table version — the per-request
    cost is a dict lookup, not an iteration over every route)."""

    __slots__ = ("exact", "prefixes", "root")

    def __init__(self, routes: Dict[str, Any]):
        self.exact: Dict[str, Tuple[str, Any]] = {}
        self.prefixes: List[Tuple[str, str, Any]] = []
        self.root: Optional[Tuple[str, Any]] = None
        for prefix, handle in routes.items():
            norm = prefix.rstrip("/") or "/"
            if norm == "/":
                self.root = ("/", handle)
                continue
            self.exact[norm] = (norm, handle)
            self.prefixes.append((norm + "/", norm, handle))
        self.prefixes.sort(key=lambda t: len(t[0]), reverse=True)

    def match(self, path: str) -> Optional[Tuple[str, Any]]:
        hit = self.exact.get(path)
        if hit is not None:
            return hit
        for pref, norm, handle in self.prefixes:
            if path.startswith(pref):
                return (norm, handle)
        return self.root


class _Admission:
    """Per-route admission control + load shedding.

    The proxy answers overload BEFORE dispatch, so excess traffic never
    reaches a replica and accepted-traffic p99 stays flat.  The budget is
    ``capacity + queue``: capacity is ``max_ongoing_requests × healthy
    replicas`` from the handle's router view (which the controller's
    health probes and the data plane's ``mark_dead`` keep current), and
    queue is sized from the route's replica-latency EWMA (the batcher's)
    so admitted-but-queued work clears within ``QUEUE_WAIT_BUDGET_S`` —
    bounding how far past an unloaded p99 an accepted request can land.

    Past the budget: a typed ``503`` with ``Retry-After`` derived from
    the same EWMA, or ``429`` when at least two clients compete and this
    one already holds its fair share of the budget (single-client
    overload is plain overload, not a fairness violation).  All counters
    live on the proxy's event loop — no lock.
    """

    __slots__ = ("handle", "inflight", "per_client", "shed_503", "shed_429")

    QUEUE_WAIT_BUDGET_S = 0.2

    def __init__(self, handle):
        self.handle = handle
        self.inflight = 0
        self.per_client: Dict[str, int] = {}
        self.shed_503 = 0
        self.shed_429 = 0

    def budget(self) -> Tuple[int, int, float]:
        """(budget, capacity, ewma_s) from the live router view."""
        state = self.handle._state
        with state.lock:
            n = len(state.replicas)
            max_ongoing = state.max_ongoing
        capacity = max(1, max_ongoing) * max(1, n)
        batcher = getattr(self.handle, "_proxy_batcher", None)
        ewma = batcher.ewma if batcher is not None else 0.0
        if ewma <= self.QUEUE_WAIT_BUDGET_S:  # fast (or cold) route
            queue = capacity
        else:  # slow route: only as much queue as clears in the budget
            queue = max(1, int(capacity * self.QUEUE_WAIT_BUDGET_S / ewma))
        return capacity + queue, capacity, ewma

    def try_admit(self, client: str):
        """``None`` admits (and counts) the request; otherwise returns
        ``(status, retry_after_s, body)`` to answer without dispatching."""
        budget, capacity, ewma = self.budget()
        if self.inflight < budget:
            self.inflight += 1
            self.per_client[client] = self.per_client.get(client, 0) + 1
            return None
        retry_after = max(1, math.ceil(ewma * self.inflight / capacity))
        n_clients = len(self.per_client)
        if n_clients >= 2:
            fair = max(1, budget // n_clients)
            if self.per_client.get(client, 0) >= fair:
                self.shed_429 += 1
                return (429, retry_after,
                        b"over per-client fair share; retry later")
        self.shed_503 += 1
        return (503, retry_after, b"deployment over capacity; retry later")

    def release(self, client: str) -> None:
        self.inflight = max(0, self.inflight - 1)
        left = self.per_client.get(client, 0) - 1
        if left <= 0:
            self.per_client.pop(client, None)
        else:
            self.per_client[client] = left

    def snapshot(self) -> Dict[str, Any]:
        budget, capacity, ewma = self.budget()
        return {"inflight": self.inflight, "budget": budget,
                "capacity": capacity, "ewma_ms": round(ewma * 1000, 3),
                "clients": len(self.per_client),
                "shed_503": self.shed_503, "shed_429": self.shed_429}


class _Batcher:
    """Per-route request coalescing (round 11, the PR-7 'fewer crossings'
    pattern applied to the data plane): while one actor call is in
    flight, every request that arrives queues here, and the next drain
    ships the WHOLE queue as one ``handle_request_batch`` call — the
    per-call submit/reply machinery (task spec, seq bookkeeping, framing,
    reply wake) amortizes across the batch.  An idle route pays nothing:
    the first request of a quiet period submits immediately with batch
    size 1 over the ordinary single-call path.  Batch size is capped at
    the deployment's ``max_ongoing_requests`` and the replica harness
    runs items concurrently on a pool of that same width, so blocking
    handlers keep the latency profile of independent calls.

    Batchmates share fate on TIMING (the call returns when the slowest
    item finishes) and on transport failure/timeout (all answer 500);
    only user exceptions are isolated per item (``_ItemError``).  That
    trade only pays where per-call overhead dominates, so coalescing is
    ADAPTIVE: an EWMA of the replica turnaround above
    ``BYPASS_LATENCY_S`` flips the route to independent per-request
    dispatch (slow handlers gain nothing from amortizing ~0.3ms of
    submit cost and would suffer head-of-line waits), and flips back
    when the route is fast again.  Batches dispatch on up to
    ``len(replicas)`` concurrent lanes, so a multi-replica route keeps
    cross-replica parallelism (one lane per replica-sized batch; a
    single-replica route pipelines exactly one batch at a time)."""

    __slots__ = ("handle", "stats", "queue", "inflight", "ewma", "_tasks")

    BYPASS_LATENCY_S = 0.05

    def __init__(self, handle, stats: _StageStats):
        self.handle = handle
        self.stats = stats
        self.queue: collections.deque = collections.deque()
        self.inflight = 0         # drain lanes currently running
        self.ewma = 0.0
        self._tasks: set = set()  # pinned: the loop's refs are weak

    def _note_latency(self, dt: float) -> None:
        self.ewma = dt if self.ewma == 0.0 else 0.8 * self.ewma + 0.2 * dt

    async def call(self, req: Request):
        if self.ewma > self.BYPASS_LATENCY_S:
            return await self._call_single(req)
        fut = asyncio.get_running_loop().create_future()
        self.queue.append((req, fut, time.perf_counter()))
        self._maybe_spawn_lane()
        return await fut

    def _maybe_spawn_lane(self):
        """Start another drain lane when work is queued and a lane is
        free — lane count is bounded by the replica count so a
        multi-replica route dispatches batches in parallel (pow2 routing
        spreads them) while a single replica pipelines one at a time."""
        lanes = max(1, len(self.handle._state.replicas))
        if not self.queue or self.inflight >= lanes:
            return
        self.inflight += 1
        # pin the task (the IoContext lesson: the loop holds only a weak
        # reference; a GC'd drainer strands every queued future)
        task = asyncio.get_running_loop().create_task(self._drain())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _call_single(self, req: Request):
        """Slow-route path: independent dispatch, no shared fate, no
        head-of-line wait behind an in-flight batch."""
        t0 = time.perf_counter()
        results, submit_t = await self._call_batch([req])
        done_t = time.perf_counter()
        self.stats.observe("queue", submit_t - t0)
        self.stats.observe("replica", done_t - submit_t)
        self.stats.batch_sizes[1] += 1
        self._note_latency(done_t - submit_t)
        return results[0]

    async def _drain(self):
        try:
            while self.queue:
                cap = max(1, self.handle._state.max_ongoing)
                batch = []
                while self.queue and len(batch) < cap:
                    batch.append(self.queue.popleft())
                self._maybe_spawn_lane()  # leftovers + a free lane: parallel
                self.stats.batch_sizes[len(batch)] += 1
                try:
                    results, submit_t = await self._call_batch(
                        [item[0] for item in batch])
                except Exception as e:  # noqa: BLE001 — whole batch failed
                    for _, fut, _enq in batch:
                        if not fut.done():
                            fut.set_exception(e)
                    continue
                done_t = time.perf_counter()
                self._note_latency(done_t - submit_t)
                if len(results) < len(batch):  # defensive: short reply
                    for _, fut, _enq in batch[len(results):]:
                        if not fut.done():
                            fut.set_exception(RuntimeError(
                                "batched reply shorter than the batch"))
                for (req, fut, enq_t), res in zip(batch, results):
                    self.stats.observe("queue", submit_t - enq_t)
                    self.stats.observe("replica", done_t - submit_t)
                    if fut.done():
                        continue
                    if isinstance(res, _ItemError):
                        fut.set_exception(res.error)
                    else:
                        fut.set_result(res)
        finally:
            self.inflight -= 1

    async def _call_batch(self, reqs: List[Request]):
        handle = self.handle
        for attempt in range(3):
            # A replica can die between routing and execution (downscale
            # drain timeout, crash) or fail with a transport-typed error
            # (ConnectionError — injected faults included): re-route the
            # WHOLE batch to a fresh replica like the reference router
            # does, so one dead replica never fails batchmates.
            if len(reqs) == 1:
                ref = await handle.remote_async(reqs[0])
            else:
                ref = await handle.remote_batch_async(
                    [((r,), {}) for r in reqs])
            submit_t = time.perf_counter()
            try:
                out = await get_async(ref, timeout=120.0)
                return (out if len(reqs) > 1 else [out]), submit_t
            except (ActorDiedError, ConnectionError, TaskError) as e:
                if isinstance(e, TaskError) and not isinstance(
                        getattr(e, "cause", None), ConnectionError):
                    raise  # a user exception — 500 is correct, no retry
                # a ConnectionError raised INSIDE the replica harness
                # (injected faults included) crosses the object plane
                # wrapped as TaskError(cause=ConnectionError): transport
                # is suspect either way, so re-route like a dead replica
                if attempt == 2:
                    raise
                dead = getattr(e, "actor_id", None)
                if dead is not None:
                    # the data plane saw the corpse before the controller
                    # did: update the router-local health view so the
                    # retry cannot land on the same dead replica
                    handle._state.mark_dead(dead)
                await handle._state.refresh_async(force=True)


class ProxyActor:
    """Ingress actor: one per cluster by default (reference ProxyActor)."""

    # request bodies buffer in the proxy before dispatch; bound them like
    # every other input dimension (413 past this)
    MAX_BODY_BYTES = 64 << 20

    def __init__(self, http_host: str = "127.0.0.1", http_port: int = 0,
                 grpc_port: Optional[int] = None):
        self._http_host = http_host
        self._http_port = http_port
        self._grpc_port = grpc_port
        self._routes: Dict[str, Any] = {}       # route_prefix -> handle
        self._matcher = _RouteMatcher({})
        self._route_version = -1
        self._server: Optional[asyncio.AbstractServer] = None
        self._grpc_server = None
        self._started = asyncio.Event()
        self._starting = False
        self._stats = _StageStats()
        # replica actor id -> supports_generator_stream (one probe RPC per
        # replica, not one per stream)
        self._gen_support: Dict[bytes, bool] = {}

    # -------------------------------------------------------------- control
    async def start(self) -> Dict[str, Any]:
        """Bind servers; returns the bound addresses. Idempotent: a second
        caller racing the first gets the already-bound address."""
        if self._server is not None or self._starting:
            await self._started.wait()
            return self.address()
        self._starting = True  # set before ANY await: guards double-bind
        self._install_hop_counter()
        try:
            self._server = await asyncio.start_server(
                self._handle_conn, self._http_host, self._http_port)
            self._http_port = self._server.sockets[0].getsockname()[1]
            await self._refresh_routes()
            if self._grpc_port is not None:
                await self._start_grpc()
        except BaseException:
            # a failed bind must not wedge every future start() behind
            # an event that will never be set
            self._starting = False
            if self._server is not None:
                self._server.close()
                self._server = None
            raise
        # pin the task: the loop holds only weak references (the IoContext
        # lesson) and a GC'd poll loop would silently freeze the route table
        self._poll_task = asyncio.get_running_loop().create_task(
            self._route_poll_loop())
        self._started.set()
        logger.info("serve proxy: http on %s:%d grpc on %s",
                    self._http_host, self._http_port, self._grpc_port)
        return {"http_host": self._http_host, "http_port": self._http_port,
                "grpc_port": self._grpc_port}

    def _install_hop_counter(self):
        """Wrap this loop's ``run_in_executor`` so EVERY executor hop
        taken on the proxy's event loop increments ``executor_hops``.
        This is what makes the zero-hop acceptance test non-vacuous: a
        future change that sneaks a thread hop back into the dispatch
        path (directly or through a helper awaited on this loop) moves
        the counter, instead of the counter being a constant 0 that
        nothing ever writes."""
        loop = asyncio.get_running_loop()
        # always (re)point at THIS proxy's stats: a restarted proxy on the
        # same worker loop must not leave the counter wired to a dead
        # predecessor's stats object (that would make it a constant zero)
        loop._rt_hop_stats = self._stats
        if getattr(loop, "_rt_hop_counted", False):
            return
        orig = loop.run_in_executor

        def counted(executor, func, *args):
            stats = getattr(loop, "_rt_hop_stats", None)
            if stats is not None:
                stats.count_executor_hop()
            return orig(executor, func, *args)

        loop.run_in_executor = counted
        loop._rt_hop_counted = True

    def address(self) -> Dict[str, Any]:
        return {"http_host": self._http_host, "http_port": self._http_port,
                "grpc_port": self._grpc_port}

    def num_requests(self) -> int:
        return self._stats.requests

    def debug_state(self) -> Dict[str, Any]:
        """Per-stage latency percentiles + executor-hop count (reference:
        proxy state in serve debug dumps).  The ``executor_hops`` field is
        the zero-threadpool acceptance hook: it counts every
        ``run_in_executor`` hop the request path took."""
        state = self._stats.snapshot()
        state["route_version"] = self._route_version
        state["routes"] = {p: h._name for p, h in self._routes.items()}
        state["admission"] = {
            p: h._proxy_admission.snapshot()
            for p, h in self._routes.items()
            if getattr(h, "_proxy_admission", None) is not None}
        return state

    async def stop(self) -> bool:
        if self._server is not None:
            self._server.close()
        if self._grpc_server is not None:
            await self._grpc_server.stop(grace=1.0)
        return True

    # ---------------------------------------------------------- route table
    def _controller(self):
        from ray_tpu.serve.api import _get_or_create_controller

        return _get_or_create_controller()

    async def _refresh_routes(self):
        from ray_tpu.serve.handle import DeploymentHandle

        controller = self._controller()
        version, table = await get_async(
            controller.get_route_table.remote(), timeout=30.0)
        if version != self._route_version:
            self._routes = {
                prefix: DeploymentHandle(app_name, controller)
                for prefix, app_name in table.items()}
            self._matcher = _RouteMatcher(self._routes)
            self._route_version = version

    async def _route_poll_loop(self):
        """Long-poll the controller: returns promptly on version change,
        every ~15 s otherwise (reference long_poll.py)."""
        controller = self._controller()
        while self._server is not None and self._server.is_serving():
            try:
                await get_async(
                    controller.listen_for_route_table.remote(
                        self._route_version), timeout=60.0)
                await self._refresh_routes()
            except Exception:  # noqa: BLE001 — controller restarting
                await asyncio.sleep(1.0)

    def _match_route(self, path: str):
        """Longest-prefix route match over the prebuilt matcher."""
        return self._matcher.match(path)

    # ------------------------------------------------------------- http
    async def _read_request(self, reader: asyncio.StreamReader
                            ) -> Optional[Tuple[Request, bool]]:
        """Parse ONE request off the connection at the bytes level.

        Returns ``(request, keep_alive)``, ``None`` at end-of-stream, or
        raises :class:`_BadRequest` — malformed input (bad request line,
        non-UTF-8 header bytes, unparsable content-length, chunked
        transfer-encoding) gets an error RESPONSE, never a silently
        killed connection."""
        line = await reader.readline()
        if not line or line in (b"\r\n", b"\n"):
            return None
        parts = line.rstrip(b"\r\n").split(b" ")
        if len(parts) != 3:
            raise _BadRequest(400, b"bad request line")
        try:
            method = parts[0].decode("ascii")
            target = parts[1].decode("ascii")
        except UnicodeDecodeError:
            raise _BadRequest(400, b"bad request line") from None
        http10 = parts[2] == b"HTTP/1.0"
        headers: Dict[str, str] = {}
        while True:
            hline = await reader.readline()
            if hline in (b"\r\n", b"\n", b""):
                break
            name_b, sep, value_b = hline.partition(b":")
            if not sep:
                raise _BadRequest(400, b"bad header line")
            try:
                name = name_b.decode("ascii").strip().lower()
                value = value_b.decode("utf-8").strip()
            except UnicodeDecodeError:
                raise _BadRequest(400, b"bad header encoding") from None
            headers[name] = value
        if "chunked" in headers.get("transfer-encoding", "").lower():
            # explicit rejection beats dispatching a silently-empty body
            raise _BadRequest(501, b"chunked transfer-encoding "
                                   b"not supported")
        try:
            length = int(headers.get("content-length", "0") or "0")
            if length < 0:
                raise ValueError
        except ValueError:
            raise _BadRequest(400, b"bad content-length") from None
        if length > self.MAX_BODY_BYTES:
            # every other input dimension is bounded; an unbounded body
            # would let one request buffer the ingress actor to death
            raise _BadRequest(413, b"body too large")
        body = await reader.readexactly(length) if length else b""
        parsed = urllib.parse.urlsplit(target)
        query = dict(urllib.parse.parse_qsl(parsed.query))
        req = Request(method=method.upper(), path=parsed.path,
                      query=query, headers=headers, body=body)
        conn_tok = headers.get("connection", "").lower()
        keep_alive = (conn_tok == "keep-alive") if http10 \
            else (conn_tok != "close")
        return req, keep_alive

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        """Connection loop: requests are served strictly in order, so a
        client may PIPELINE requests on one keep-alive connection and
        responses come back in request order (HTTP/1.1 semantics)."""
        try:
            while True:
                try:
                    parsed = await self._read_request(reader)
                except _BadRequest as e:
                    # the framing is no longer trustworthy: answer, then
                    # close THIS connection — the listener stays healthy
                    await self._write_simple(writer, e.status, e.message)
                    return
                except ValueError:
                    # a line over the stream reader's limit (readline
                    # raises) — still a malformed request, still answered
                    await self._write_simple(writer, 400,
                                             b"request line/header too long")
                    return
                if parsed is None:
                    return
                req, keep_alive = parsed
                await self._dispatch(req, writer)
                if not keep_alive:
                    return
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _dispatch(self, req: Request, writer: asyncio.StreamWriter):
        if req.path == "/-/routes":  # reference exposes the route table
            table = {p: h._name for p, h in self._routes.items()}
            await self._write_response(
                writer, 200, "application/json", json.dumps(table).encode())
            return
        if req.path == "/-/healthz":
            await self._write_response(writer, 200, "text/plain", b"ok")
            return
        clock = self._stats.clock()
        match = self._match_route(req.path)
        if match is None:
            await self._refresh_routes()
            match = self._match_route(req.path)
        clock.lap("route")
        if match is None:
            await self._write_simple(writer, 404, b"no matching route")
            clock.finish()  # failed requests must not vanish from 'total'
            return
        prefix, handle = match
        admission = getattr(handle, "_proxy_admission", None)
        if admission is None:
            admission = _Admission(handle)
            handle._proxy_admission = admission
        client = req.headers.get("x-client-id") or self._peer_key(writer)
        try:
            # the budget reads capacity off the router view; refresh it
            # first (a cached no-op within REFRESH_INTERVAL_S) so
            # admission tracks `max_ongoing × healthy replicas`, not the
            # cold-handle default
            await handle._state.refresh_async()
        except Exception:  # noqa: BLE001 — stale view beats failing closed
            pass
        shed = admission.try_admit(client)
        if shed is not None:
            # Load shedding happens HERE, before any dispatch work: the
            # replica never sees the request, so accepted traffic keeps
            # its latency profile while excess gets a typed answer.
            status, retry_after, msg = shed
            self._stats.count_shed(status)
            await self._write_response(writer, status, "text/plain", msg,
                                       {"retry-after": str(retry_after)})
            clock.finish()
            return
        try:
            if req.headers.get("accept") == "text/event-stream":
                await self._dispatch_stream(req, handle, writer, clock)
                return
            batcher = getattr(handle, "_proxy_batcher", None)
            if batcher is None:
                batcher = _Batcher(handle, self._stats)
                handle._proxy_batcher = batcher
            try:
                # Dispatch + reply wait are awaits on THIS loop — no thread
                # hop, no blocking get; concurrent arrivals coalesce into one
                # batched actor call (the batcher records queue/replica laps).
                result = await batcher.call(req)
            except Exception as e:  # noqa: BLE001 — replica/user error → 500
                await self._write_response(
                    writer, 500, "text/plain",
                    f"deployment error: {e}".encode()[:4096])
                # tail latency during incidents must include the failures —
                # a 'total' computed only from successes understates exactly
                # when it matters
                clock.finish()
                return
            clock.skip()
            status, ctype, body, extra = _render(result)
            clock.lap("render")
            await self._write_response(writer, status, ctype, body, extra)
            clock.lap("write")
            clock.finish()
        finally:
            # SSE streams hold their admission slot for the whole stream
            # life (they run inside this try), so long streams count
            # toward the route budget exactly like in-flight unary calls.
            admission.release(client)

    @staticmethod
    def _peer_key(writer: asyncio.StreamWriter) -> str:
        """Fair-share client identity: explicit ``x-client-id`` header
        wins (set by trusted edge LBs); otherwise the peer address."""
        peer = writer.get_extra_info("peername")
        return peer[0] if isinstance(peer, tuple) else str(peer)

    # --------------------------------------------------------------- sse
    async def _replica_supports_generator(self, replica) -> bool:
        key = replica._actor_id.binary()
        cached = self._gen_support.get(key)
        if cached is not None:
            return cached
        try:
            supports = await get_async(
                replica.supports_generator_stream.remote(), timeout=30.0)
        except Exception:  # noqa: BLE001 — older replica OR a transient
            # probe failure: use the poll protocol for THIS stream but do
            # NOT cache, or one slow probe would pin a push-capable
            # replica to the poll path for the proxy's lifetime
            return False
        if len(self._gen_support) > 4096:
            self._gen_support.clear()  # bound the cache across redeploys
        self._gen_support[key] = supports
        return supports

    async def _dispatch_stream(self, req: Request, handle,
                               writer: asyncio.StreamWriter,
                               clock: _StageClock):
        """SSE streaming.  Replicas exposing a generator ``stream`` method
        ride the streaming-generator protocol — PUSH-based: each item
        wakes this loop directly and ``drain`` backpressure propagates a
        slow client to the replica.  The submit/poll protocol survives
        only as a fallback for pre-generator replicas."""
        # Sticky routing: the stream must hit ONE replica for its whole
        # life (generator state / request id live in that replica).
        await handle._state.refresh_async()
        acquired = handle._state.acquire_replica()
        if acquired is None:
            await self._write_response(writer, 500, "text/plain",
                                       b"no running replicas")
            return
        replica, ridx = acquired
        clock.lap("queue")
        try:
            if await self._replica_supports_generator(replica):
                self._stats.stream_protocols["generator"] += 1
                await self._stream_via_generator(req, replica, writer)
            else:
                self._stats.stream_protocols["poll"] += 1
                await self._stream_via_poll(req, replica, writer)
        finally:
            handle._state.release(ridx)
            clock.lap("replica")
            clock.finish()

    async def _stream_via_generator(self, req, replica,
                                    writer: asyncio.StreamWriter):
        gen = replica.handle_request_stream.options(
            num_returns="streaming").remote((req,), {})
        try:
            writer.write(b"HTTP/1.1 200 OK\r\n"
                         b"content-type: text/event-stream\r\n"
                         b"cache-control: no-cache\r\n"
                         b"transfer-encoding: chunked\r\n\r\n")
            await writer.drain()
            # push path: __anext__ parks on the stream state and the
            # producer's report wakes this loop; awaiting drain() before
            # the next item is the client-side backpressure that (via the
            # owner's delayed report replies) throttles the replica
            async for ref in gen:
                chunk = await get_async(ref, timeout=60.0)
                if isinstance(chunk, SSEBatch):
                    await self._write_chunks(
                        writer,
                        [b"data: " + json.dumps(c).encode() + b"\n\n"
                         for c in chunk])
                else:
                    await self._write_chunk(
                        writer,
                        b"data: " + json.dumps(chunk).encode() + b"\n\n")
            await self._write_chunk(writer, b"data: [DONE]\n\n")
        except (ConnectionError, OSError):
            gen.close()  # consumer gone: cancel the stream at the replica
            return
        except Exception as e:  # noqa: BLE001
            try:
                await self._write_chunk(
                    writer,
                    b"event: error\ndata: " + str(e).encode() + b"\n\n")
            except Exception:  # noqa: BLE001
                pass
        try:
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except Exception:  # noqa: BLE001
            pass

    async def _stream_via_poll(self, req, replica,
                               writer: asyncio.StreamWriter):
        """Legacy submit/poll protocol (pre-generator replicas): the
        deployment implements ``submit(request) -> req_id`` and
        ``poll(req_id) -> {"chunks": [...], "done": bool}``."""
        try:
            req_id = await get_async(
                replica.handle_request.remote("submit", (req,), {}),
                timeout=60.0)
        except Exception as e:  # noqa: BLE001
            await self._write_response(
                writer, 500, "text/plain",
                f"stream submit failed: {e}".encode()[:4096])
            return
        try:
            writer.write(b"HTTP/1.1 200 OK\r\n"
                         b"content-type: text/event-stream\r\n"
                         b"cache-control: no-cache\r\n"
                         b"transfer-encoding: chunked\r\n\r\n")
            await writer.drain()
            while True:
                out = await get_async(
                    replica.handle_request.remote("poll", (req_id,), {}),
                    timeout=60.0)
                for chunk in out.get("chunks", ()):
                    payload = json.dumps(chunk).encode()
                    await self._write_chunk(
                        writer, b"data: " + payload + b"\n\n")
                if out.get("done"):
                    await self._write_chunk(writer, b"data: [DONE]\n\n")
                    break
                await asyncio.sleep(0.02)
        except (ConnectionError, OSError):
            return
        except Exception as e:  # noqa: BLE001
            try:
                await self._write_chunk(
                    writer, b"event: error\ndata: " + str(e).encode() + b"\n\n")
            except Exception:  # noqa: BLE001
                pass
        try:
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except Exception:  # noqa: BLE001
            pass

    @staticmethod
    async def _write_chunk(writer: asyncio.StreamWriter, data: bytes):
        faults.fault_point("serve.proxy.write")
        writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        await writer.drain()

    _REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
                413: "Payload Too Large", 429: "Too Many Requests",
                500: "Internal Server Error", 501: "Not Implemented",
                503: "Service Unavailable"}

    @classmethod
    async def _write_response(cls, writer: asyncio.StreamWriter, status: int,
                              ctype: str, body: bytes,
                              extra: Optional[Dict[str, str]] = None):
        # FaultInjected is a ConnectionError: an injected write fault
        # tears THIS connection (the conn loop's handler closes it) and
        # nothing else — the listener and other connections stay healthy.
        faults.fault_point("serve.proxy.write")
        # ONE coalesced write per response (head + body in a single
        # buffer hand-off); drain is a no-op below the transport
        # high-water mark, so pipelined small responses never stall here
        reason = cls._REASONS.get(status, "OK")
        head = [f"HTTP/1.1 {status} {reason}",
                f"content-type: {ctype}",
                f"content-length: {len(body)}"]
        for k, v in (extra or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
        await writer.drain()

    @staticmethod
    async def _write_simple(writer, status: int, msg: bytes):
        await ProxyActor._write_response(writer, status, "text/plain", msg)

    @staticmethod
    async def _write_chunks(writer: asyncio.StreamWriter, parts: List[bytes]):
        """Several SSE events, ONE buffer hand-off + drain."""
        buf = bytearray()
        for data in parts:
            buf += f"{len(data):x}\r\n".encode() + data + b"\r\n"
        writer.write(bytes(buf))
        await writer.drain()

    # ------------------------------------------------------------- grpc
    async def _start_grpc(self):
        """Generic unary gRPC ingress: /<app>/<method>, pickled payloads
        (reference gRPCProxy:520 serves user protos; we stay proto-less).
        Same async-native dispatch as HTTP: no executor hop."""
        import grpc

        proxy = self

        class Generic(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                path = handler_call_details.method  # "/<app>/<method>"

                async def unary(request_bytes, context):
                    _, app, method = path.split("/", 2)
                    handle = None
                    for prefix, h in proxy._routes.items():
                        if h._name == app or prefix.strip("/") == app:
                            handle = h
                            break
                    if handle is None:
                        await proxy._refresh_routes()
                        for prefix, h in proxy._routes.items():
                            if h._name == app or prefix.strip("/") == app:
                                handle = h
                                break
                    if handle is None:
                        # outside any try: abort signals by raising and must
                        # not be re-wrapped as INTERNAL
                        await context.abort(grpc.StatusCode.NOT_FOUND,
                                            f"no deployment {app!r}")
                    try:
                        args, kwargs = pickle.loads(request_bytes) \
                            if request_bytes else ((), {})
                        ref = await handle.options(method).remote_async(
                            *args, **kwargs)
                        result = await get_async(ref, timeout=120.0)
                        return pickle.dumps(result)
                    except Exception as e:  # noqa: BLE001
                        await context.abort(grpc.StatusCode.INTERNAL, str(e))

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b)

        from grpc import aio as grpc_aio

        self._grpc_server = grpc_aio.server()
        self._grpc_server.add_generic_rpc_handlers((Generic(),))
        bound = self._grpc_server.add_insecure_port(
            f"{self._http_host}:{self._grpc_port or 0}")
        self._grpc_port = bound
        await self._grpc_server.start()
