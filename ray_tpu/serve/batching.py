"""@serve.batch — request coalescing inside a replica.

Reference: ``python/ray/serve/batching.py`` — decorated method receives a
LIST of requests; concurrent callers are queued until ``max_batch_size``
or ``batch_wait_timeout_s`` and executed as one call. The TPU motivation
is stronger than the GPU one: batched matmuls keep the MXU full, and the
LLM path builds its continuous batching on the same queue primitive.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, List, Optional


class _Waiter:
    __slots__ = ("value", "error", "event")

    def __init__(self):
        self.value: Any = None
        self.error: Optional[BaseException] = None
        self.event = threading.Event()


class _BatchQueue:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = timeout_s
        self._lock = threading.Lock()
        self._pending: List[tuple] = []
        self._flush_timer: Optional[threading.Timer] = None

    def submit(self, instance, item) -> Any:
        waiter = _Waiter()
        run_now: Optional[List[tuple]] = None
        with self._lock:
            self._pending.append((instance, item, waiter))
            if len(self._pending) >= self._max:
                run_now, self._pending = self._pending, []
                if self._flush_timer is not None:
                    self._flush_timer.cancel()
                    self._flush_timer = None
            elif self._flush_timer is None:
                self._flush_timer = threading.Timer(self._timeout,
                                                    self._flush)
                self._flush_timer.daemon = True
                self._flush_timer.start()
        if run_now is not None:
            self._run(run_now)
        else:
            waiter.event.wait()
        if waiter.error is not None:
            raise waiter.error
        return waiter.value

    def _flush(self):
        with self._lock:
            batch, self._pending = self._pending, []
            self._flush_timer = None
        if batch:
            self._run(batch)

    def _run(self, batch: List[tuple]):
        instance = batch[0][0]
        items = [b[1] for b in batch]
        waiters = [b[2] for b in batch]
        try:
            if instance is not None:
                results = self._fn(instance, items)
            else:
                results = self._fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"@batch function returned {len(results)} results for "
                    f"{len(items)} inputs")
            for w, r in zip(waiters, results):
                w.value = r
        except BaseException as e:  # noqa: BLE001 — fan error to callers
            for w in waiters:
                w.error = e
        for w in waiters:
            w.event.set()


# Per-process queue registry: _BatchQueue holds threading primitives that
# must NOT ride along when cloudpickle ships the decorated class to a
# replica — queues are (re)created lazily in whichever process calls.
_QUEUES: dict = {}
_QUEUES_LOCK = threading.Lock()


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorate a (self, requests: List) -> List method or a
    (requests: List) -> List function."""

    def wrap(fn):
        import uuid

        key = f"{getattr(fn, '__qualname__', 'batch_fn')}:{uuid.uuid4().hex}"

        def get_queue(instance) -> _BatchQueue:
            # Queues are keyed per (function, instance): two instances of a
            # batched class must never coalesce into each other's batches.
            # Reach the registry via the module: cloudpickle serializes a
            # by-value function's referenced globals BY VALUE, and the
            # registry lock must never ride along to replicas.
            import ray_tpu.serve.batching as B

            qkey = (key, id(instance))
            q = B._QUEUES.get(qkey)
            if q is None:
                with B._QUEUES_LOCK:
                    q = B._QUEUES.setdefault(
                        qkey, B._BatchQueue(fn, max_batch_size,
                                            batch_wait_timeout_s))
            return q

        @functools.wraps(fn)
        def method_wrapper(self_or_item, *rest):
            if rest:                      # bound method: (self, item)
                return get_queue(self_or_item).submit(self_or_item, rest[0])
            return get_queue(None).submit(None, self_or_item)

        return method_wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
