"""Tiny important-by-name Serve app used by the declarative-deploy tests
and as the ``import_path`` reference example (reference: the
``fruit.py``/``conditional_dag.py`` example apps the reference's serve
CLI docs deploy by import path).
"""

from __future__ import annotations

from ray_tpu.serve.deployment import make_deployment


@make_deployment
class Echo:
    """Echoes its input, tagged with the configured prefix."""

    def __init__(self, prefix: str = "echo"):
        self.prefix = prefix

    def __call__(self, value="?"):
        return f"{self.prefix}:{value}"


# a ready-bound Application (import_path "...:app")
app = Echo.bind("echo")


def build_app(prefix: str = "built"):
    """Builder-function form (import_path "...:build_app" with args)."""
    return Echo.bind(prefix)
