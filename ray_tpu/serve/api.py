"""Serve public API (reference ``python/ray/serve/api.py``)."""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import Application, make_deployment
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.proxy import PROXY_NAME, ProxyActor, Request, Response

deployment = make_deployment

_lock = threading.Lock()
_controller = None
_proxy = None
_proxy_addr = None


def _get_or_create_controller():
    global _controller
    import ray_tpu

    with _lock:
        if _controller is not None:
            return _controller
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
        except Exception:  # noqa: BLE001 — not started yet
            remote_cls = ray_tpu.remote(ServeController)
            # infinite restarts: a crashed controller comes back and
            # re-applies the declarative spec persisted in the GCS KV
            # (schema.py) — programmatic-only apps die with it, as in the
            # reference without a checkpointed config
            _controller = remote_cls.options(
                name=CONTROLLER_NAME, max_concurrency=16,
                max_restarts=-1).remote()
        return _controller


def _deploy_tree(app: Application, controller, deployed: dict,
                 name: Optional[str] = None) -> DeploymentHandle:
    """Model composition (reference ``serve.run(Driver.bind(A.bind(),
    B.bind()))``): nested Applications in init args/kwargs deploy first
    (depth-first) and are replaced by their DeploymentHandles — handles
    pickle across the process boundary, so the driver replica receives
    live handles to its sub-models."""
    import cloudpickle

    import ray_tpu

    def resolve(v):
        if isinstance(v, Application):
            return _deploy_tree(v, controller, deployed)
        return v

    dep = app.deployment
    app_name = name or dep.name
    if app_name in deployed:
        return deployed[app_name]
    init_args = tuple(resolve(a) for a in app.init_args)
    init_kwargs = {k: resolve(v) for k, v in app.init_kwargs.items()}
    ray_tpu.get([controller.deploy.remote(
        app_name, cloudpickle.dumps(dep),
        cloudpickle.dumps(dep.func_or_class),
        init_args, init_kwargs)])
    handle = DeploymentHandle(app_name, controller)
    deployed[app_name] = handle
    return handle


def run(app: Application, *, name: Optional[str] = None,
        blocking: bool = False, wait_timeout_s: float = 60.0
        ) -> DeploymentHandle:
    """Deploy an application — including any nested Applications bound
    as init args (model composition) — and return the top handle
    (reference ``serve.run``)."""
    import time

    import ray_tpu

    controller = _get_or_create_controller()
    deployed: dict = {}
    handle = _deploy_tree(app, controller, deployed, name=name)
    # wait for at least one replica of EVERY deployed app (children
    # included: the driver's first call must not race their boot)
    deadline = time.monotonic() + wait_timeout_s
    for app_name in deployed:
        while True:
            _, replicas, *_ = ray_tpu.get(
                [controller.get_replicas.remote(app_name)])[0]
            if replicas:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replica of {app_name!r} became ready")
            time.sleep(0.1)
    if blocking:  # pragma: no cover — interactive use
        while True:
            time.sleep(1)
    return handle


def start(http_host: str = "127.0.0.1", http_port: int = 0,
          grpc_port: Optional[int] = 0) -> Dict[str, Any]:
    """Start the ingress proxy (HTTP + optional gRPC); idempotent.
    Returns the bound addresses (reference: serve.start / ProxyActor)."""
    global _proxy, _proxy_addr
    import ray_tpu

    import time as _time

    with _lock:
        if _proxy_addr is not None:
            return dict(_proxy_addr)
    _get_or_create_controller()
    with _lock:
        if _proxy is None:
            try:
                _proxy = ray_tpu.get_actor(PROXY_NAME)
            except Exception:  # noqa: BLE001 — not started yet
                remote_cls = ray_tpu.remote(ProxyActor)
                _proxy = remote_cls.options(
                    name=PROXY_NAME, max_concurrency=64).remote(
                        http_host, http_port, grpc_port)
        proxy = _proxy
    # start() is idempotent on the actor; poll until the listener is bound
    # so a port of 0 (pre-bind) is never cached or returned.
    addr = ray_tpu.get([proxy.start.remote()], timeout=60.0)[0]
    deadline = _time.monotonic() + 60.0
    while not addr.get("http_port") and _time.monotonic() < deadline:
        _time.sleep(0.1)
        addr = ray_tpu.get([proxy.address.remote()], timeout=30.0)[0]
    with _lock:
        if _proxy_addr is None and addr.get("http_port"):
            _proxy_addr = addr
    return dict(addr)


def deploy_config(config: Optional[Dict[str, Any]] = None, *,
                  app=None, name: str = "default",
                  wait: bool = True, timeout_s: float = 120.0
                  ) -> Dict[str, Any]:
    """Declarative deploy (reference: ``serve deploy`` + ``PUT
    /api/serve/applications/``): persist a validated app spec in the GCS
    KV; the controller reconciles running apps onto it — across its own
    restarts.  Pass either a full config dict (see serve/schema.py) or a
    bound ``app`` (cloudpickled into the spec for un-importable apps).
    Returns the apply status."""
    import json
    import time as _time

    import ray_tpu
    from ray_tpu.core_worker.worker import CoreWorker
    from ray_tpu.serve import schema

    if (config is None) == (app is None):
        raise ValueError("pass exactly one of config / app")
    if app is not None:
        config = {"applications": [
            {"name": name, "pickled_app": schema.pack_application(app)}]}
    doc = schema.make_config_doc(config)
    _get_or_create_controller()  # controller watches the KV key
    gcs = CoreWorker.current_or_raise().gcs
    gcs.kv_put(schema.KV_NAMESPACE, schema.KV_CONFIG_KEY,
               json.dumps(doc).encode(), overwrite=True)
    if not wait:
        return {"version": doc["version"], "apps": {}}
    deadline = _time.monotonic() + timeout_s
    want = {a["name"] for a in doc["config"]["applications"]}
    while _time.monotonic() < deadline:
        raw = gcs.kv_get(schema.KV_NAMESPACE, schema.KV_APPLY_STATUS_KEY)
        if raw:
            st = json.loads(raw)
            if st.get("version") == doc["version"]:
                failed = {n: s for n, s in st["apps"].items()
                          if s.get("state") == "DEPLOY_FAILED"}
                if failed:
                    raise RuntimeError(f"declarative deploy failed: {failed}")
                live = ray_tpu.get(
                    [_get_or_create_controller().status.remote()])[0]
                if all(live.get(n, {}).get("running_replicas", 0) > 0
                       for n in want):
                    return st
        _time.sleep(0.2)
    raise TimeoutError("declarative deploy did not converge "
                       f"within {timeout_s:.0f}s")


def get_declarative_config() -> Optional[Dict[str, Any]]:
    """The spec currently persisted in the GCS KV (None = none)."""
    import json

    from ray_tpu.core_worker.worker import CoreWorker
    from ray_tpu.serve import schema

    raw = CoreWorker.current_or_raise().gcs.kv_get(
        schema.KV_NAMESPACE, schema.KV_CONFIG_KEY)
    return json.loads(raw) if raw else None


def llm_app(model: str = "tiny", *, name: str = "llm",
            num_replicas: int = 1, num_slots: int = 8,
            speculation=None, ray_actor_options: Optional[dict] = None,
            **engine_kwargs) -> Application:
    """Build a bound LLM-serving Application — the declarative-config
    entry point for TPU LLM replicas (``import_path:
    "ray_tpu.serve.api:llm_app"`` with ``args: {model: ..., speculation:
    {method: draft, draft_model: ..., k: ...}}``). ``speculation`` is
    validated eagerly (SpeculationConfig.parse — the same rules the
    config schema applies, minus its JSON-only restriction), so a bad
    spec fails at deploy time.

    Prefix caching: pass ``prefix_cache="radix"`` (with optional
    ``prefix_cache_bytes``) through ``engine_kwargs`` and set the
    deployment override ``request_router: prefix_aware`` so the handle
    routes shared-prefix traffic at the replica whose radix tree
    already holds it."""
    from ray_tpu.models.speculation import SpeculationConfig
    from ray_tpu.serve.llm import LLMServer

    if speculation is not None:
        # validate eagerly, but hand the ORIGINAL spec to the engine:
        # programmatic draft_config/draft_params objects are legal here
        # (schema.validate_speculation would reject them — its canonical
        # JSON form is for declarative configs, which must name a
        # draft_model instead). Same rules the engine applies at boot:
        # thread the sibling spec_k default and check draft_model
        # membership now, not minutes later on the replica.
        cfg = SpeculationConfig.parse(
            speculation, default_k=int(engine_kwargs.get("spec_k", 4)))
        if cfg.draft_model is not None and cfg.draft_config is None:
            from ray_tpu.models import llama

            if cfg.draft_model not in llama.CONFIGS:
                raise ValueError(
                    f"speculation draft_model {cfg.draft_model!r}: not "
                    f"in {sorted(llama.CONFIGS)}")
        engine_kwargs["speculation"] = speculation
        engine_kwargs.setdefault("kv_cache", "slot")
    # real TPU replicas must pin device resources or they schedule onto
    # non-TPU nodes (LLMServer docstring: ray_actor_options={"num_tpus": N})
    dep = make_deployment(LLMServer, name=name,
                          num_replicas=num_replicas,
                          ray_actor_options=ray_actor_options)
    return dep.bind(model=model, num_slots=num_slots, **engine_kwargs)


def proxy_address() -> Optional[Dict[str, Any]]:
    return dict(_proxy_addr) if _proxy_addr else None


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name, _get_or_create_controller())


def status() -> Dict[str, Any]:
    import ray_tpu

    return ray_tpu.get([_get_or_create_controller().status.remote()])[0]


def delete(name: str):
    import ray_tpu

    ray_tpu.get([_get_or_create_controller().delete_app.remote(name)])


def shutdown():
    global _controller, _proxy, _proxy_addr
    import ray_tpu

    with _lock:
        proxy, _proxy, _proxy_addr = _proxy, None, None
    if proxy is not None:
        try:
            ray_tpu.get([proxy.stop.remote()], timeout=10.0)
            ray_tpu.kill(proxy)
        except Exception:  # noqa: BLE001
            pass
    with _lock:
        if _controller is None:
            return
        try:
            ray_tpu.get([_controller.shutdown.remote()], timeout=30.0)
            ray_tpu.kill(_controller)
        except Exception:  # noqa: BLE001 — cluster may already be down
            pass
        _controller = None
