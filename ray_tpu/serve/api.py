"""Serve public API (reference ``python/ray/serve/api.py``)."""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import Application, make_deployment
from ray_tpu.serve.handle import DeploymentHandle

deployment = make_deployment

_lock = threading.Lock()
_controller = None


def _get_or_create_controller():
    global _controller
    import ray_tpu

    with _lock:
        if _controller is not None:
            return _controller
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
        except Exception:  # noqa: BLE001 — not started yet
            remote_cls = ray_tpu.remote(ServeController)
            _controller = remote_cls.options(
                name=CONTROLLER_NAME, max_concurrency=16).remote()
        return _controller


def run(app: Application, *, name: Optional[str] = None,
        blocking: bool = False, wait_timeout_s: float = 60.0
        ) -> DeploymentHandle:
    """Deploy an application; returns its handle
    (reference ``serve.run``)."""
    import time

    import cloudpickle

    import ray_tpu

    controller = _get_or_create_controller()
    dep = app.deployment
    app_name = name or dep.name
    ray_tpu.get([controller.deploy.remote(
        app_name, cloudpickle.dumps(dep),
        cloudpickle.dumps(dep.func_or_class),
        app.init_args, app.init_kwargs)])
    handle = DeploymentHandle(app_name, controller)
    # wait for at least one replica
    deadline = time.monotonic() + wait_timeout_s
    while True:
        _, replicas, _ = ray_tpu.get(
            [controller.get_replicas.remote(app_name)])[0]
        if replicas:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(f"no replica of {app_name!r} became ready")
        time.sleep(0.1)
    if blocking:  # pragma: no cover — interactive use
        while True:
            time.sleep(1)
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name, _get_or_create_controller())


def status() -> Dict[str, Any]:
    import ray_tpu

    return ray_tpu.get([_get_or_create_controller().status.remote()])[0]


def delete(name: str):
    import ray_tpu

    ray_tpu.get([_get_or_create_controller().delete_app.remote(name)])


def shutdown():
    global _controller
    import ray_tpu

    with _lock:
        if _controller is None:
            return
        try:
            ray_tpu.get([_controller.shutdown.remote()], timeout=30.0)
            ray_tpu.kill(_controller)
        except Exception:  # noqa: BLE001 — cluster may already be down
            pass
        _controller = None
