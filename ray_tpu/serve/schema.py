"""Declarative Serve app specs (reference: ``python/ray/serve/schema.py``
``ServeDeploySchema`` / ``ServeApplicationSchema`` and the ``serve deploy``
CLI + ``PUT /api/serve/applications/`` REST route).

A config is data, not code::

    applications:
      - name: text_app
        import_path: my_pkg.serving:app      # Application or builder fn
        route_prefix: /text
        args: {model: "1b"}                  # builder-fn kwargs
        deployments:                          # per-deployment overrides
          - name: TextModel
            num_replicas: 2

The validated config is persisted in the GCS KV (``serve`` /
``declarative_config``); the Serve controller watches that key and
reconciles the running apps to it — so the spec survives controller
crashes and restarts (the reference persists the same schema in its
controller checkpoint).  ``pickled_app`` (base64 cloudpickle of a bound
Application) is an internal alternative to ``import_path`` used by
``serve.deploy_config(app=...)`` when the app isn't importable by name.
"""

from __future__ import annotations

import base64
from typing import Any, Dict, List

KV_NAMESPACE = "serve"
KV_CONFIG_KEY = b"declarative_config"
KV_APPLY_STATUS_KEY = b"declarative_apply_status"

# deployment-level fields an operator may override without touching code
_DEPLOYMENT_OVERRIDES = (
    "num_replicas", "max_ongoing_requests", "route_prefix",
    "request_router", "graceful_shutdown_timeout_s",
)


class ServeConfigError(ValueError):
    pass


def validate_speculation(spec, default_k: int = 4) -> Dict[str, Any]:
    """Canonicalize a speculative-decoding spec (method string or dict —
    see ``ray_tpu.models.speculation.SpeculationConfig``) into its
    JSON-able form. Declarative LLM apps carry this under
    ``args.speculation`` (vLLM parity: the reference forwards
    ``speculative_config`` to the vLLM engine).

    The canonical form is what the replica boots from, and it cannot
    carry live ``draft_config``/``draft_params`` objects — a draft spec
    whose only source is an object is rejected HERE, at deploy time,
    instead of passing validation and failing replica boot minutes
    later (programmatic callers with real objects go through
    ``serve.api.llm_app``, which forwards the originals)."""
    from ray_tpu.models.speculation import SpeculationConfig

    try:
        cfg = SpeculationConfig.parse(spec, default_k=default_k)
    except ValueError as e:
        raise ServeConfigError(f"speculation: {e}") from e
    if cfg.method == "draft":
        if cfg.draft_model is None:
            raise ServeConfigError(
                "speculation: draft_config/draft_params objects are not "
                "JSON-serializable — declarative configs must name a "
                "draft_model (ray_tpu.models.llama.CONFIGS)")
        from ray_tpu.models import llama

        if cfg.draft_model not in llama.CONFIGS:
            raise ServeConfigError(
                f"speculation: draft_model {cfg.draft_model!r} is not in "
                f"{sorted(llama.CONFIGS)}")
    return cfg.to_dict()


def validate_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Validate + normalize a deploy config dict.  Returns the canonical
    form; raises ServeConfigError with a field path on bad input."""
    if not isinstance(config, dict):
        raise ServeConfigError("config must be a mapping")
    apps = config.get("applications")
    if not isinstance(apps, list) or not apps:
        raise ServeConfigError("config.applications must be a non-empty list")
    out_apps: List[Dict[str, Any]] = []
    seen = set()
    for i, app in enumerate(apps):
        where = f"applications[{i}]"
        if not isinstance(app, dict):
            raise ServeConfigError(f"{where} must be a mapping")
        name = app.get("name")
        if not name or not isinstance(name, str):
            raise ServeConfigError(f"{where}.name is required")
        if name in seen:
            raise ServeConfigError(f"duplicate application name {name!r}")
        seen.add(name)
        has_import = isinstance(app.get("import_path"), str)
        has_blob = isinstance(app.get("pickled_app"), str)
        if has_import == has_blob:
            raise ServeConfigError(
                f"{where} needs exactly one of import_path / pickled_app")
        if has_import and ":" not in app["import_path"]:
            raise ServeConfigError(
                f"{where}.import_path must look like 'module.sub:attr'")
        args = app.get("args") or {}
        if not isinstance(args, dict):
            raise ServeConfigError(f"{where}.args must be a mapping")
        if args.get("speculation") is not None:
            # canonicalize eagerly so a bad spec fails the deploy call,
            # not the replica boot minutes later; thread the sibling
            # spec_k kwarg through so a spec with no explicit k inherits
            # it instead of pinning the canonical form to the default
            try:
                default_k = int(args.get("spec_k", 4))
            except (TypeError, ValueError):
                raise ServeConfigError(
                    f"{where}.args.spec_k must be an integer, got "
                    f"{args['spec_k']!r}") from None
            try:
                args = dict(args,
                            speculation=validate_speculation(
                                args["speculation"],
                                default_k=default_k))
            except ServeConfigError as e:
                # e already reads "speculation: ..." — just add the path
                raise ServeConfigError(f"{where}.args.{e}") from e
        if args.get("prefix_cache") is not None:
            # same reject-at-deploy-time contract as speculation: the
            # engine enforces these in __init__, but a typo'd mode
            # should fail the deploy call, not the replica boot
            pc = args["prefix_cache"]
            if pc not in ("radix", "legacy", "off"):
                raise ServeConfigError(
                    f"{where}.args.prefix_cache must be 'radix', "
                    f"'legacy' or 'off', got {pc!r}")
        if args.get("prefix_cache_bytes") is not None:
            try:
                pcb = int(args["prefix_cache_bytes"])
                if pcb < 0:
                    raise ValueError
            except (TypeError, ValueError):
                raise ServeConfigError(
                    f"{where}.args.prefix_cache_bytes must be a "
                    f"non-negative integer, got "
                    f"{args['prefix_cache_bytes']!r}") from None
            args = dict(args, prefix_cache_bytes=pcb)
        deployments = app.get("deployments") or []
        if not isinstance(deployments, list):
            raise ServeConfigError(f"{where}.deployments must be a list")
        norm_deps = []
        for j, d in enumerate(deployments):
            dw = f"{where}.deployments[{j}]"
            if not isinstance(d, dict) or not d.get("name"):
                raise ServeConfigError(f"{dw} needs a name")
            unknown = set(d) - {"name", *_DEPLOYMENT_OVERRIDES}
            if unknown:
                raise ServeConfigError(
                    f"{dw} has unknown fields {sorted(unknown)}; "
                    f"overridable: {sorted(_DEPLOYMENT_OVERRIDES)}")
            norm_deps.append(dict(d))
        entry: Dict[str, Any] = {"name": name, "args": args,
                                 "deployments": norm_deps}
        if has_import:
            entry["import_path"] = app["import_path"]
        else:
            entry["pickled_app"] = app["pickled_app"]
        if app.get("route_prefix") is not None:
            rp = app["route_prefix"]
            if not isinstance(rp, str) or not rp.startswith("/"):
                raise ServeConfigError(
                    f"{where}.route_prefix must start with '/'")
            entry["route_prefix"] = rp
        out_apps.append(entry)
    return {"applications": out_apps}


def make_config_doc(config: Dict[str, Any]) -> Dict[str, Any]:
    """Validate and wrap a config into the one canonical KV document
    shape — every submission path (python API, CLI, dashboard REST) MUST
    build the doc here so version-matching stays consistent."""
    import time

    return {"version": time.time_ns(), "config": validate_config(config)}


def pack_application(app) -> str:
    """cloudpickle an in-memory bound Application into the config's
    ``pickled_app`` transport form."""
    import cloudpickle

    return base64.b64encode(cloudpickle.dumps(app)).decode()


def resolve_application(entry: Dict[str, Any]):
    """Materialize an app entry: import (or unpickle) and, for builder
    functions, call with ``args``.  Returns a bound Application."""
    from ray_tpu.serve.deployment import Application

    if "pickled_app" in entry:
        import cloudpickle

        app = cloudpickle.loads(base64.b64decode(entry["pickled_app"]))
    else:
        import importlib

        mod_name, _, attr = entry["import_path"].partition(":")
        obj = getattr(importlib.import_module(mod_name), attr)
        app = obj(**entry.get("args", {})) if callable(obj) \
            and not isinstance(obj, Application) else obj
    if not isinstance(app, Application):
        raise ServeConfigError(
            f"app {entry['name']!r} resolved to {type(app).__name__}, "
            "expected a bound Application (use Deployment.bind())")
    return app


def apply_overrides(app, entry: Dict[str, Any]) -> None:
    """Apply the config's per-deployment overrides + app-level
    route_prefix onto the resolved deployment objects (in place —
    Applications are built fresh per apply)."""
    from ray_tpu.serve.deployment import Application

    deps_by_name = {}

    def collect(a):
        deps_by_name[a.deployment.name] = a.deployment
        for v in list(a.init_args) + list(a.init_kwargs.values()):
            if isinstance(v, Application):
                collect(v)

    collect(app)
    if entry.get("route_prefix") is not None:
        app.deployment.route_prefix = entry["route_prefix"]
    for d in entry.get("deployments", []):
        dep = deps_by_name.get(d["name"])
        if dep is None:
            raise ServeConfigError(
                f"override for unknown deployment {d['name']!r} "
                f"(have: {sorted(deps_by_name)})")
        for k, v in d.items():
            if k != "name":
                setattr(dep, k, v)
