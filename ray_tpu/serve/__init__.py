"""Serve library — model serving on TPU replicas.

Reference architecture (SURVEY.md §3.6, reference ``python/ray/serve/``):
a controller reconciles target application/deployment state into replica
actors; handles route requests with power-of-two-choices; replicas
autoscale on queue metrics; ``@serve.batch`` coalesces requests. TPU
divergence: replicas pin TPU chips and the LLM path
(:mod:`ray_tpu.serve.llm`) does continuous batching over a compiled
decode step instead of delegating to vLLM.
"""

from ray_tpu.serve.api import (  # noqa: F401
    delete,
    deploy_config,
    deployment,
    get_declarative_config,
    get_deployment_handle,
    proxy_address,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.handle import DeploymentHandle  # noqa: F401
from ray_tpu.serve.multiplex import (  # noqa: F401
    get_multiplexed_model_id,
    multiplexed,
)
from ray_tpu.serve.proxy import Request, Response  # noqa: F401

from ray_tpu.util.usage import record_library_usage as _record_usage
_record_usage("serve")
del _record_usage
