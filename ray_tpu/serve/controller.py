"""Serve controller + replica harness.

Reference: ``python/ray/serve/_private/controller.py:90`` (ServeController
actor), ``deployment_state.py`` (replica FSM reconciliation),
``autoscaling_state.py`` (queue-metric autoscaling). One actor owns target
state; a reconcile thread converges actual replica actors to target and
autoscales between min/max replicas on observed ongoing-request load.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from ray_tpu.common import faults

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"


class _ItemError:
    """Per-item failure inside a batched call: the other items' results
    still flow; the proxy re-raises this one for its own request only."""

    def __init__(self, error: BaseException):
        self.error = error


class Replica:
    """Replica harness actor: wraps the user callable, tracks load
    (reference ``python/ray/serve/_private/replica.py``)."""

    def __init__(self, cls_blob: bytes, init_args, init_kwargs,
                 max_ongoing: int = 8, version: int = 0):
        import cloudpickle

        cls = cloudpickle.loads(cls_blob)
        self._user = cls(*init_args, **init_kwargs)
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        self._max_ongoing = max(1, int(max_ongoing))
        self._version = int(version)
        self._batch_pool = None  # lazy: only batched callers pay for it

    def ping(self) -> bool:
        # A user-defined check_health() makes the controller's probe see
        # application health, not just process liveness (reference:
        # Serve replica health checks call the user's check_health).
        check = getattr(self._user, "check_health", None)
        if callable(check):
            check()
        return True

    def pid(self) -> int:
        """Worker process pid — chaos tests SIGKILL a replica through this."""
        return os.getpid()

    def version(self) -> int:
        return self._version

    def get_metrics(self) -> Dict[str, Any]:
        from ray_tpu.serve import multiplex

        with self._lock:
            return {"ongoing": float(self._ongoing),
                    "total": float(self._total),
                    "version": self._version,
                    "model_ids": multiplex.loaded_model_ids(self._user)}

    def get_prefix_digest(self) -> List[int]:
        """Compact prefix-cache advertisement for prefix-aware routing.

        Delegates to the user object's ``prefix_digest()`` when it has
        one (the LLM server exposes its radix tree's chunk hashes);
        anything else — no method, or a digest that fails mid-walk —
        degrades to an empty hint, never an error: the digest is purely
        a routing optimization."""
        fn = getattr(self._user, "prefix_digest", None)
        if not callable(fn):
            return []
        try:
            return [int(h) for h in fn()]
        except Exception:  # noqa: BLE001 — hint only
            return []

    def supports_generator_stream(self) -> bool:
        import inspect

        fn = getattr(self._user, "stream", None)
        return fn is not None and inspect.isgeneratorfunction(fn)

    def handle_request_stream(self, args, kwargs):
        """Generator-protocol streaming: the user's ``stream`` generator's
        items push to the caller via ``num_returns="streaming"`` —
        per-item delivery with owner-side backpressure, no poll RPCs
        (reference: Serve response streaming over ObjectRefGenerator)."""
        faults.fault_point("serve.replica.stream")
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            yield from self._user.stream(*args, **kwargs)
        finally:
            with self._lock:
                self._ongoing -= 1

    def handle_request_batch(self, method: str, calls):
        """Coalesced dispatch (round 11): the proxy ships every request
        queued behind an in-flight call as ONE actor call, amortizing the
        per-call submit/reply machinery.  Items run CONCURRENTLY on the
        harness pool (sized to ``max_ongoing_requests``) so a batch of
        blocking handlers keeps the latency profile of independent calls;
        per-item exceptions come back as :class:`_ItemError` so one bad
        request cannot fail its batchmates.  Transport-typed failures
        (``ConnectionError``, which includes injected faults) are the
        exception to per-item isolation: they mean THIS replica's
        transport is suspect, so the whole call raises and the proxy
        re-routes the entire batch to a fresh replica instead of handing
        batchmates a 500."""
        if len(calls) == 1:
            args, kwargs = calls[0]
            try:
                return [self.handle_request(method, args, kwargs)]
            except ConnectionError:
                raise  # whole-call failure: proxy retries on a fresh replica
            except Exception as e:  # noqa: BLE001 — per-item isolation
                return [_ItemError(e)]
        if self._batch_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._batch_pool = ThreadPoolExecutor(
                max_workers=self._max_ongoing,
                thread_name_prefix="replica-batch")

        def run(args, kwargs):
            try:
                return self.handle_request(method, args, kwargs)
            except Exception as e:  # noqa: BLE001 — per-item isolation
                return _ItemError(e)

        futures = [self._batch_pool.submit(run, a, k) for a, k in calls]
        results = [f.result() for f in futures]
        for res in results:
            if isinstance(res, _ItemError) and isinstance(
                    res.error, ConnectionError):
                raise res.error
        return results

    def handle_request(self, method: str, args, kwargs):
        from ray_tpu.serve import multiplex

        faults.fault_point("serve.replica.call")
        with self._lock:
            self._ongoing += 1
            self._total += 1
        token = multiplex.set_request_model_id(
            kwargs.pop("_multiplexed_model_id", ""))
        try:
            target = (self._user if method == "__call__"
                      else getattr(self._user, method))
            if method == "__call__" and not callable(self._user):
                raise TypeError("deployment class is not callable")
            return target(*args, **kwargs)
        finally:
            multiplex.reset_request_model_id(token)
            with self._lock:
                self._ongoing -= 1


class ServeController:
    """Target-state reconciler (runs as a detached-ish named actor)."""

    RECONCILE_INTERVAL_S = 0.25
    PING_FAILURE_THRESHOLD = 3
    PING_TIMEOUT_S = 10.0

    def __init__(self):
        # name -> {"deployment": Deployment, "blob": bytes, "args", "kwargs",
        #          "replicas": [handles], "target": int}
        self._apps: Dict[str, dict] = {}
        self._lock = threading.RLock()
        self._version = 0
        self._route_version = 0
        self._draining: List[dict] = []  # {"replica", "since"}
        self._ping_failures: Dict[str, int] = {}
        from ray_tpu.util.metrics import Gauge

        self._ongoing_gauge = Gauge(
            "rt_serve_ongoing_requests",
            "in-flight requests summed over an app's replicas",
            tag_keys=("app",))
        # declarative mode (schema.py): version of the KV config this
        # incarnation has applied, and the app names it owns.  Starts at
        # None so a freshly (re)started controller re-applies whatever
        # spec is persisted — THAT is what makes the spec survive
        # controller crashes (reference: controller checkpoint recovery).
        self._declarative_version = None
        self._declarative_apps: set = set()
        self._declarative_hashes: Dict[str, str] = {}
        # transiently-failed app deploys are retried (the spec still
        # declares them) — with a floor between attempts so a persistent
        # import error doesn't spam every reconcile tick
        self._declarative_retry_at = 0.0
        # {app name: (monotonic stamp, {replica idx: digest})} — see
        # get_prefix_digests
        self._digest_cache: Dict[str, tuple] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._reconcile_loop,
                                        daemon=True, name="serve-reconcile")
        self._thread.start()

    # ------------------------------------------------------------- deploy
    def deploy(self, name: str, deployment_blob: bytes, cls_blob: bytes,
               init_args, init_kwargs) -> bool:
        import cloudpickle

        dep = cloudpickle.loads(deployment_blob)
        target = (dep.autoscaling_config.min_replicas
                  if dep.autoscaling_config else dep.num_replicas)
        abandoned: List[Any] = []
        with self._lock:
            prev = self._apps.get(name)
            if prev is None:
                self._apps[name] = {
                    "deployment": dep,
                    "cls_blob": cls_blob,
                    "args": init_args,
                    "kwargs": init_kwargs,
                    "replicas": [],
                    "target": target,
                    "version": 1,
                    "next": None,
                }
            else:
                # Rolling upgrade (reference: deployment_state.py rolling
                # update): the OLD replica set keeps serving while the new
                # version's replicas start and warm; the reconcile thread
                # swaps serving sets only once every new replica answers a
                # ping, then drains the old set.  Requests arriving
                # mid-roll therefore always land on a live, warm replica.
                old_next = prev.get("next")
                if old_next:
                    abandoned = list(old_next["replicas"])
                prev["next"] = {
                    "deployment": dep,
                    "cls_blob": cls_blob,
                    "args": init_args,
                    "kwargs": init_kwargs,
                    "replicas": [],
                    "target": target,
                    "version": prev.get("version", 1) + 1,
                }
            self._version += 1
            self._route_version += 1
        self._kill_replicas(abandoned)
        return True

    def _kill_replicas(self, replicas) -> None:
        import ray_tpu

        for r in replicas:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass

    def delete_app(self, name: str) -> bool:
        with self._lock:
            app = self._apps.pop(name, None)
            self._digest_cache.pop(name, None)
            self._version += 1
            self._route_version += 1
        if app:
            self._kill_replicas(app["replicas"])
            if app.get("next"):
                self._kill_replicas(app["next"]["replicas"])
        return True

    def shutdown(self) -> bool:
        self._stop.set()
        for name in list(self._apps):
            self.delete_app(name)
        return True

    # ------------------------------------------------------------- queries
    def get_replicas(self, name: str):
        """(version, replica handles, max_ongoing, router) for handle
        routing."""
        with self._lock:
            app = self._apps.get(name)
            if app is None:
                raise KeyError(f"no deployment named {name!r}")
            return (self._version, list(app["replicas"]),
                    app["deployment"].max_ongoing_requests,
                    getattr(app["deployment"], "request_router", "pow2"))

    def get_prefix_digests(self, name: str) -> Dict[int, List[int]]:
        """{replica index -> prefix digest} for prefix-aware routing.

        Fanned out to the app's replicas with a short timeout and cached
        briefly: handles refresh on a poll loop, and the digest is a
        routing *hint* — a couple seconds of staleness just means a
        request lands on the second-best replica and warms it instead.
        Indices line up with the replica list ``get_replicas`` returns
        at the same version; dead/slow replicas simply contribute no
        entry."""
        import ray_tpu

        now = time.monotonic()
        with self._lock:
            cached = self._digest_cache.get(name)
            if cached is not None and now - cached[0] < 2.0:
                return cached[1]
            app = self._apps.get(name)
            replicas = list(app["replicas"]) if app else []
        out: Dict[int, List[int]] = {}
        for i, r in enumerate(replicas):
            try:
                d = ray_tpu.get([r.get_prefix_digest.remote()],
                                timeout=3.0)[0]
                if d:
                    out[i] = [int(h) for h in d]
            except Exception:  # noqa: BLE001 — hint only
                continue
        with self._lock:
            self._digest_cache[name] = (now, out)
        return out

    def get_route_table(self):
        """(version, {route_prefix: app_name}) for the ingress proxies."""
        with self._lock:
            table = {}
            for name, app in self._apps.items():
                prefix = app["deployment"].route_prefix or f"/{name}"
                table[prefix] = name
            return self._route_version, table

    async def listen_for_route_table(self, known_version: int,
                                     timeout_s: float = 15.0):
        """Long-poll (reference long_poll.py): returns when the route table
        version moves past ``known_version`` or after ``timeout_s``."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._route_version != known_version:
                    return self._route_version
            await asyncio.sleep(0.1)
        with self._lock:
            return self._route_version

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                name: {
                    "target_replicas": app["target"],
                    "running_replicas": len(app["replicas"]),
                    "autoscaling": app["deployment"].autoscaling_config
                    is not None,
                    "version": app.get("version", 1),
                    "rolling": app.get("next") is not None,
                }
                for name, app in self._apps.items()
            }

    # ----------------------------------------------------------- reconcile
    def _reconcile_loop(self):
        while not self._stop.is_set():
            try:
                self._check_declarative()
            except Exception:  # noqa: BLE001 — bad spec must not stop
                logger.error("declarative apply error:\n%s",
                             traceback.format_exc())
            try:
                self._reconcile_once()
                self._publish_status()
            except Exception:  # noqa: BLE001 — keep the loop alive
                logger.error("reconcile error:\n%s", traceback.format_exc())
            self._stop.wait(self.RECONCILE_INTERVAL_S)

    # ---------------------------------------------------- declarative mode
    def _check_declarative(self):
        """Converge running apps onto the spec persisted in the GCS KV
        (serve/schema.py).  Runs every reconcile tick; cheap no-op while
        the version is unchanged."""
        import json

        from ray_tpu.core_worker.worker import CoreWorker
        from ray_tpu.serve import schema

        try:
            gcs = CoreWorker.current_or_raise().gcs
            raw = gcs.kv_get(schema.KV_NAMESPACE, schema.KV_CONFIG_KEY)
        except Exception:  # noqa: BLE001 — GCS hiccup: retry next tick
            return
        if not raw:
            return
        doc = json.loads(raw)
        version = doc.get("version")
        if version == self._declarative_version:
            return
        if time.monotonic() < self._declarative_retry_at:
            return  # backing off after a failed apply of this version
        status: dict = {"version": version, "apps": {}}
        config = schema.validate_config(doc.get("config") or {})
        import ray_tpu
        from ray_tpu.serve.api import _deploy_tree

        own_handle = ray_tpu.get_actor(CONTROLLER_NAME)
        wanted = set()
        for entry in config["applications"]:
            name = entry["name"]
            wanted.add(name)
            # unchanged entries keep their running replicas: a config bump
            # that only touches app B must not drain-and-replace app A
            entry_hash = json.dumps(entry, sort_keys=True)
            if (self._declarative_hashes.get(name) == entry_hash
                    and name in self._apps):
                status["apps"][name] = {"state": "UNCHANGED"}
                continue
            try:
                app = schema.resolve_application(entry)
                schema.apply_overrides(app, entry)
                _deploy_tree(app, own_handle, {}, name=name)
                self._declarative_hashes[name] = entry_hash
                status["apps"][name] = {"state": "DEPLOYED"}
            except Exception as e:  # noqa: BLE001 — per-app isolation:
                # one bad import must not block the other apps
                logger.error("declarative deploy of %r failed:\n%s",
                             name, traceback.format_exc())
                status["apps"][name] = {"state": "DEPLOY_FAILED",
                                        "error": repr(e)}
        # apps this controller previously declared but the new spec drops
        for gone in self._declarative_apps - wanted:
            self.delete_app(gone)
            self._declarative_hashes.pop(gone, None)
            status["apps"][gone] = {"state": "DELETED"}
        self._declarative_apps = wanted
        failed = any(s.get("state") == "DEPLOY_FAILED"
                     for s in status["apps"].values())
        if failed:
            # leave the version unlatched: failed apps are re-attempted
            # (succeeded ones skip via their entry hash) every 5s
            self._declarative_retry_at = time.monotonic() + 5.0
        else:
            self._declarative_version = version
        try:
            gcs.kv_put(schema.KV_NAMESPACE, schema.KV_APPLY_STATUS_KEY,
                       json.dumps(status).encode(), overwrite=True)
        except Exception:  # noqa: BLE001 — status is best-effort
            pass

    def _publish_status(self):
        """Drop the app table into GCS KV so the dashboard's Serve view
        reads controller state without a handle to this actor
        (reference: the Serve dashboard module reads controller
        checkpoints from the GCS KV)."""
        import json

        try:
            from ray_tpu.core_worker.worker import CoreWorker

            gcs = CoreWorker.current_or_raise().gcs
            payload = {"apps": self.status(), "updated_at": time.time()}
            gcs.kv_put("serve", b"status",
                       json.dumps(payload).encode(), overwrite=True)
        except Exception:  # noqa: BLE001 — dashboarding must never
            pass           # interfere with reconciliation

    def _enqueue_drain(self, replica, dep) -> None:
        """Must be called with self._lock held.  The drain deadline is the
        deployment's own graceful_shutdown_timeout_s — in-flight work
        (including SSE streams, which hold ``ongoing`` > 0 for their whole
        lifetime) gets that long to finish before the replica is killed."""
        self._draining.append({
            "replica": replica,
            "since": time.monotonic(),
            "timeout": getattr(dep, "graceful_shutdown_timeout_s", 10.0),
        })

    def _drain_old_replicas(self):
        import ray_tpu

        with self._lock:
            draining = list(self._draining)
        still = []
        for d in draining:
            r, since = d["replica"], d["since"]
            done = False
            try:
                m = ray_tpu.get([r.get_metrics.remote()], timeout=3.0)[0]
                done = m["ongoing"] <= 0
            except Exception:  # noqa: BLE001 — dead already
                done = True
            if done or time.monotonic() - since > d.get("timeout", 10.0):
                try:
                    ray_tpu.kill(r)
                except Exception:  # noqa: BLE001
                    pass
            else:
                still.append(d)
        with self._lock:
            self._draining = still

    def _advance_rollouts(self):
        """Drive in-progress rolling upgrades: start the next version's
        replicas, wait for every one to answer a ping (warm), then swap
        the serving set atomically and drain the old one.  A next-version
        replica that fails ``PING_FAILURE_THRESHOLD`` consecutive probes
        is replaced; while a roll cannot complete the OLD set keeps
        serving, so a broken new version degrades to a stalled roll —
        never to 5xx."""
        import ray_tpu

        with self._lock:
            rolling = [(name, app) for name, app in self._apps.items()
                       if app.get("next")]
        for name, app in rolling:
            nxt = app["next"]
            while True:
                with self._lock:
                    if app.get("next") is not nxt:  # restaged mid-start
                        break
                    need = nxt["target"] - len(nxt["replicas"])
                if need <= 0:
                    break
                r = self._start_replica(name, nxt)
                with self._lock:
                    if app.get("next") is nxt:
                        nxt["replicas"].append(r)
                    else:
                        self._kill_replicas([r])
                        break
            ready = 0
            for i, r in enumerate(list(nxt["replicas"])):
                key = r._actor_id.hex()
                try:
                    faults.fault_point("serve.controller.probe")
                    ray_tpu.get([r.ping.remote()],
                                timeout=self.PING_TIMEOUT_S)
                    self._ping_failures.pop(key, None)
                    ready += 1
                except Exception:  # noqa: BLE001 — still warming or dead
                    fails = self._ping_failures.get(key, 0) + 1
                    self._ping_failures[key] = fails
                    if fails >= self.PING_FAILURE_THRESHOLD:
                        logger.warning(
                            "next-version replica of %s failed %d probes "
                            "during rollout; replacing", name, fails)
                        self._ping_failures.pop(key, None)
                        self._kill_replicas([r])
                        nxt["replicas"][i] = self._start_replica(name, nxt)
            if ready < nxt["target"]:
                continue
            with self._lock:
                if self._apps.get(name) is not app or app.get("next") is not nxt:
                    continue  # app deleted or roll restaged meanwhile
                old_replicas = app["replicas"]
                old_dep = app["deployment"]
                app.update(
                    deployment=nxt["deployment"],
                    cls_blob=nxt["cls_blob"],
                    args=nxt["args"],
                    kwargs=nxt["kwargs"],
                    replicas=nxt["replicas"],
                    target=nxt["target"],
                    version=nxt["version"],
                    next=None,
                )
                for r in old_replicas:
                    self._enqueue_drain(r, old_dep)
                self._version += 1
                self._route_version += 1
            logger.info("rolled %s to version %d (%d replicas warm)",
                        name, app["version"], len(app["replicas"]))

    def _reconcile_once(self):
        import ray_tpu

        self._drain_old_replicas()
        self._advance_rollouts()
        with self._lock:
            apps = list(self._apps.items())
        for name, app in apps:
            dep = app["deployment"]
            # Health check with a consecutive-failure threshold (reference
            # gcs_health_check_manager failure_threshold): one slow ping
            # under load must not get a busy replica killed.  Ejection
            # bumps self._version, so every handle's next refresh (≤
            # REFRESH_INTERVAL_S) stops routing to the unhealthy replica.
            alive = []
            for r in app["replicas"]:
                key = r._actor_id.hex()
                try:
                    faults.fault_point("serve.controller.probe")
                    ray_tpu.get([r.ping.remote()],
                                timeout=self.PING_TIMEOUT_S)
                    self._ping_failures.pop(key, None)
                    alive.append(r)
                except Exception:  # noqa: BLE001 — slow or dead
                    fails = self._ping_failures.get(key, 0) + 1
                    self._ping_failures[key] = fails
                    if fails < self.PING_FAILURE_THRESHOLD:
                        alive.append(r)
                    else:
                        logger.warning(
                            "replica of %s failed %d health checks; "
                            "replacing", name, fails)
                        self._ping_failures.pop(key, None)
                        # drain rather than drop: if it is merely wedged
                        # on a long request it finishes then dies; the
                        # drain timeout bounds a truly-hung one
                        with self._lock:
                            self._enqueue_drain(r, dep)
            changed = len(alive) != len(app["replicas"])

            # Mid-roll, the serving target is frozen: autoscale decisions
            # would fight the swap that is about to replace the set.
            if (dep.autoscaling_config is not None and alive
                    and not app.get("next")):
                app["target"] = self._autoscale_target(dep, alive,
                                                       app["target"])

            while len(alive) < app["target"]:
                alive.append(self._start_replica(name, app))
                changed = True
            while len(alive) > app["target"]:
                # Graceful downscale: drain, don't kill mid-request
                # (reference deployment_state graceful_shutdown).
                victim = alive.pop()
                with self._lock:
                    self._enqueue_drain(victim, dep)
                changed = True
            with self._lock:
                if name in self._apps:
                    self._apps[name]["replicas"] = alive
                    if changed:
                        self._version += 1

    def _start_replica(self, name: str, spec: dict):
        """``spec`` is either an app dict or its staged ``next`` dict —
        both carry deployment/cls_blob/args/kwargs/version."""
        import ray_tpu

        dep = spec["deployment"]
        opts = dict(dep.ray_actor_options)
        opts.setdefault("max_concurrency", dep.max_ongoing_requests)
        # Deployment scheduler (reference
        # serve/_private/deployment_scheduler.py): replicas of one
        # deployment SPREAD across nodes by default, so one node's death
        # never takes the whole deployment down and per-node proxies have
        # a local replica to route to. Explicit strategies win.
        opts.setdefault("scheduling_strategy", "SPREAD")
        remote_cls = ray_tpu.remote(Replica)
        logger.info("starting replica of %s (version %d)",
                    name, spec.get("version", 1))
        return remote_cls.options(**opts).remote(
            spec["cls_blob"], spec["args"], spec["kwargs"],
            max_ongoing=dep.max_ongoing_requests,
            version=spec.get("version", 1))

    def _autoscale_target(self, dep, replicas: List[Any],
                          current: int) -> int:
        import ray_tpu

        cfg = dep.autoscaling_config
        try:
            metrics = ray_tpu.get(
                [r.get_metrics.remote() for r in replicas], timeout=5.0)
        except Exception:  # noqa: BLE001 — skip this round
            return current
        ongoing = sum(m["ongoing"] for m in metrics)
        self._ongoing_gauge.set(ongoing, tags={"app": dep.name})
        per_replica = ongoing / max(len(replicas), 1)
        if per_replica > cfg.target_ongoing_requests * cfg.upscale_threshold:
            return min(current + 1, cfg.max_replicas)
        if per_replica < cfg.target_ongoing_requests * cfg.downscale_threshold:
            return max(current - 1, cfg.min_replicas)
        return current
