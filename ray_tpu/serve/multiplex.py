"""Model multiplexing: many models time-sharing one replica pool.

Reference: ``python/ray/serve/multiplex.py`` (_ModelMultiplexWrapper),
``python/ray/serve/api.py`` (``@serve.multiplexed``,
``serve.get_multiplexed_model_id``). A deployment decorates a loader
``def get_model(model_id)`` with ``@serve.multiplexed(max_num_models_per_
replica=N)``; each replica then caches up to N loaded models with LRU
eviction, and the handle routes a request tagged
``handle.options(multiplexed_model_id="m1")`` to a replica that already
holds the model (model-affinity routing in ``handle._RouterState``).

TPU framing: "loading a model" is typically staging weights into the
replica's chip HBM — eviction really frees device memory, so the LRU cap
is the HBM budget knob. Loads are serialized per replica (one compile /
HBM-staging at a time) like the reference's per-wrapper lock.
"""

from __future__ import annotations

import contextvars
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Callable, List, Optional

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rt_serve_multiplexed_model_id", default="")

# Wrappers alive in this process, weakly held: a deleted replica's
# wrapper (and the models it caches) must be collectable, not pinned by
# this introspection registry.
_REGISTRY: "weakref.WeakSet[_ModelMultiplexWrapper]" = weakref.WeakSet()
_REGISTRY_LOCK = threading.Lock()


def get_multiplexed_model_id() -> str:
    """Inside a replica handling a request: the model id the caller set
    via ``handle.options(multiplexed_model_id=...)`` (reference:
    ``serve.get_multiplexed_model_id``)."""
    return _current_model_id.get()


def loaded_model_ids(scope: Any = None) -> List[str]:
    """Model ids currently loaded. With ``scope`` (a deployment
    instance), only that instance's wrappers — the replica harness uses
    this so each replica reports its own placement; without it, the
    union across the process (debug introspection)."""
    if scope is not None:
        wrappers = [w for w in getattr(scope, "__dict__", {}).values()
                    if isinstance(w, _ModelMultiplexWrapper)]
    else:
        with _REGISTRY_LOCK:
            wrappers = list(_REGISTRY)
    out: List[str] = []
    for w in wrappers:
        out.extend(w.model_ids())
    return out


class _ModelMultiplexWrapper:
    """LRU cache of model_id -> loaded model around a user loader fn."""

    def __init__(self, loader: Callable[..., Any], max_models: int):
        if max_models < 1:
            raise ValueError("max_num_models_per_replica must be >= 1")
        self._loader = loader
        self._max = max_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self._load_s = 0.0  # cumulative load time, for metrics
        self.__name__ = getattr(loader, "__name__", "multiplexed")
        with _REGISTRY_LOCK:
            _REGISTRY.add(self)

    def model_ids(self) -> List[str]:
        with self._lock:
            return list(self._models)

    def _evict_lru(self) -> None:
        mid, model = self._models.popitem(last=False)
        del mid
        # Reference calls __del__ semantics: drop the reference and let
        # finalizers free device memory; an explicit unload hook wins.
        unload = getattr(model, "unload", None)
        if callable(unload):
            try:
                unload()
            except Exception:  # noqa: BLE001 — eviction must not fail
                pass

    def load(self, model_id: Optional[str] = None, *args: Any) -> Any:
        """Return the loaded model for ``model_id`` (default: the current
        request's multiplexed id), loading + LRU-evicting as needed."""
        mid = model_id if model_id is not None else _current_model_id.get()
        if not mid:
            raise ValueError(
                "no model id: pass one explicitly or set "
                "handle.options(multiplexed_model_id=...) on the caller")
        with self._lock:
            if mid in self._models:
                self._models.move_to_end(mid)
                return self._models[mid]
            # load outside? Reference serializes loads per wrapper; with
            # the lock held the load also blocks lookups, matching the
            # one-load-at-a-time behavior and keeping eviction atomic.
            while len(self._models) >= self._max:
                self._evict_lru()
            t0 = time.monotonic()
            model = self._loader(mid, *args)
            self._load_s += time.monotonic() - t0
            self._models[mid] = model
            return model

    # the decorated loader is called like the original function
    __call__ = load


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator form (reference ``serve.multiplexed``)::

        class LLMHost:
            @serve.multiplexed(max_num_models_per_replica=2)
            def get_model(self, model_id: str):
                return load_weights_to_hbm(model_id)

            def __call__(self, prompt):
                model = self.get_model(serve.get_multiplexed_model_id())
                ...

    Methods are supported: the wrapper binds per-instance on first
    access so each replica instance gets its own LRU cache.
    """

    def wrap(fn: Callable):
        return _MultiplexedDescriptor(fn, max_num_models_per_replica)

    return wrap(func) if func is not None else wrap


class _MultiplexedDescriptor:
    """Descriptor so ``@multiplexed`` works on methods and functions."""

    def __init__(self, fn: Callable, max_models: int):
        self._fn = fn
        self._max = max_models
        self._plain: Optional[_ModelMultiplexWrapper] = None
        self._attr = f"__rt_multiplex_{id(self)}"

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        wrapper = getattr(obj, self._attr, None)
        if wrapper is None:
            bound = self._fn.__get__(obj, objtype)
            wrapper = _ModelMultiplexWrapper(bound, self._max)
            setattr(obj, self._attr, wrapper)
        return wrapper

    def __call__(self, *args, **kwargs):  # plain-function use
        if self._plain is None:
            self._plain = _ModelMultiplexWrapper(self._fn, self._max)
        return self._plain(*args, **kwargs)


def set_request_model_id(model_id: str) -> contextvars.Token:
    """Replica harness: bind the request's model id for the duration of
    the user call (pops on reset)."""
    return _current_model_id.set(model_id or "")


def reset_request_model_id(token: contextvars.Token) -> None:
    _current_model_id.reset(token)
