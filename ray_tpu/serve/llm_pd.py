"""Prefill/decode disaggregation for LLM serving.

Reference: ``python/ray/llm/_internal/serve/deployments/
prefill_decode_disagg/`` — prefill and decode run as separate Serve
deployments so the bursty, compute-bound prefill fleet scales
independently of the steady, memory-bound decode fleet; there the KV
moves between vLLM instances via NIXL/NCCL. TPU-native version: the
prefill replica computes the prompt KV with the jitted prefill program,
ships it as plain arrays over the serve transport (shm object plane
same-node, chunked RPC across nodes), and the decode replica injects it
into a slot with one fused ``dynamic_update_slice`` per cache array
(:func:`ray_tpu.models.decoding.make_inject`) — no re-prefill on the
decode side.

Deploy with :func:`build_pd_app`::

    handles = build_pd_app(model="tiny", prefill_replicas=1,
                           decode_replicas=1)
    out = ray_tpu.get(handles.remote([1, 2, 3], max_tokens=8))
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class PrefillServer:
    """Prefill-only replica: one-slot cache, returns the prompt KV.

    Scale this deployment with prompt traffic; it holds the same params
    as the decode fleet (same model + seed) but only ever runs the
    prefill program.
    """

    def __init__(self, model: str = "tiny", seed: int = 0,
                 max_seq: Optional[int] = None):
        import threading

        import jax

        from ray_tpu.models import llama
        from ray_tpu.models.decoding import init_cache, make_prefill

        self.config = llama.CONFIGS[model]
        self.params = llama.init_params(self.config, jax.random.key(seed))
        self.max_seq = max_seq or self.config.max_seq
        self._cache = init_cache(self.config, 1, self.max_seq)
        self._prefill = make_prefill(self.params, self.config)
        # replica actors run handle_request with max_concurrency > 1 and
        # prefill donates the cache buffer: calls must serialize
        self._lock = threading.Lock()

    def __call__(self, prompt: List[int]) -> Dict[str, Any]:
        with self._lock:
            return self._prefill_one(prompt)

    def _prefill_one(self, prompt: List[int]) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.decoding import pad_to_bucket

        plen = len(prompt)
        if plen == 0:
            raise ValueError("empty prompt")
        P = min(pad_to_bucket(plen), self.max_seq)
        tokens = np.zeros((1, P), np.int32)
        tokens[0, :plen] = prompt
        self._cache, logits = self._prefill(
            self._cache, jnp.asarray(tokens), plen, 0)
        k, v, lg = jax.device_get((self._cache["k"][:, 0, :plen],
                                   self._cache["v"][:, 0, :plen], logits))
        return {"k": np.asarray(k), "v": np.asarray(v),
                "logits": np.asarray(lg), "len": plen}


class DecodeServer:
    """Decode-only replica: full slot engine, admits prefilled KV."""

    def __init__(self, model: str = "tiny", num_slots: int = 8,
                 seed: int = 0, max_seq: Optional[int] = None,
                 prefix_cache_size: int = 0):
        from ray_tpu.serve.llm import LLMEngine

        self.engine = LLMEngine(model=model, num_slots=num_slots, seed=seed,
                                max_seq=max_seq,
                                prefix_cache_size=prefix_cache_size)

    def submit_prefilled(self, prompt: List[int], kv: Any,
                         max_tokens: int = 64, temperature: float = 0.0,
                         eos_token: Optional[int] = None) -> str:
        from ray_tpu.core_worker.reference import ObjectRef

        if isinstance(kv, ObjectRef):
            # KV shipped by reference: resolve from the object plane HERE
            # (the payload goes prefill replica -> object store -> this
            # process, skipping the orchestrator entirely)
            import ray_tpu

            kv = ray_tpu.get(kv, timeout=120.0)
        return self.engine.submit_prefilled(
            prompt, kv["k"], kv["v"], kv["logits"], max_tokens=max_tokens,
            temperature=temperature, eos_token=eos_token)

    def poll(self, request_id: str) -> Dict[str, Any]:
        return self.engine.poll(request_id)

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def __del__(self):
        try:
            self.engine.shutdown()
        except Exception:  # noqa: BLE001
            pass


class PDOrchestrator:
    """Ingress deployment gluing the two fleets: route the prompt to a
    prefill replica, hand the KV to a decode replica, stream tokens.

    The KV crosses replica boundaries as a value through the object
    plane — the orchestrator never copies it into its own process twice
    (it passes the prefill reply straight through).
    """

    def __init__(self, prefill_handle, decode_handle,
                 poll_interval_s: float = 0.01):
        import ray_tpu

        self._rt = ray_tpu
        self.prefill = prefill_handle
        self.decode = decode_handle
        self._poll_interval = poll_interval_s

    def __call__(self, prompt: List[int], max_tokens: int = 64,
                 temperature: float = 0.0,
                 eos_token: Optional[int] = None,
                 timeout_s: float = 300.0) -> List[int]:
        import time

        # the KV ObjectRef passes through UNTOUCHED: the decode replica
        # resolves it from the object plane, so the payload never lands
        # in the orchestrator process
        kv_ref = self.prefill.remote(list(prompt))
        # Sticky routing: submit and every poll must hit the SAME decode
        # replica (the request id lives in that replica's engine state) —
        # same idiom as the proxy's SSE path (proxy.py _dispatch_stream).
        self.decode._state.refresh()
        acquired = self.decode._state.acquire_replica()
        if acquired is None:
            raise RuntimeError("no running decode replicas")
        replica, ridx = acquired
        try:
            rid = self._rt.get(
                replica.handle_request.remote(
                    "submit_prefilled", (list(prompt), kv_ref),
                    {"max_tokens": max_tokens, "temperature": temperature,
                     "eos_token": eos_token}),
                timeout=timeout_s)
            out: List[int] = []
            deadline = time.monotonic() + timeout_s
            while True:
                r = self._rt.get(
                    replica.handle_request.remote("poll", (rid,), {}),
                    timeout=timeout_s)
                out.extend(r["chunks"])
                if r["done"]:
                    return out
                if time.monotonic() > deadline:
                    raise TimeoutError("PD generation timed out")
                time.sleep(self._poll_interval)
        finally:
            self.decode._state.release(ridx)

    def stats(self) -> Dict[str, Any]:
        """Aggregate engine stats over every decode replica."""
        self.decode._state.refresh()
        replicas = list(self.decode._state.replicas)
        per = self._rt.get(
            [r.handle_request.remote("stats", (), {}) for r in replicas])
        out: Dict[str, Any] = {}
        for s in per:
            for key, val in s.items():
                out[key] = out.get(key, 0) + val
        return out


def build_pd_app(model: str = "tiny", *, prefill_replicas: int = 1,
                 decode_replicas: int = 1, num_slots: int = 8,
                 seed: int = 0, max_seq: Optional[int] = None,
                 name: str = "llm-pd"):
    """Deploy prefill fleet + decode fleet + orchestrator; returns the
    orchestrator's DeploymentHandle."""
    from ray_tpu import serve

    prefill_dep = serve.deployment(
        PrefillServer, name=f"{name}-prefill",
        num_replicas=prefill_replicas)
    decode_dep = serve.deployment(
        DecodeServer, name=f"{name}-decode", num_replicas=decode_replicas)
    serve.run(prefill_dep.bind(model=model, seed=seed, max_seq=max_seq))
    serve.run(decode_dep.bind(model=model, num_slots=num_slots, seed=seed,
                              max_seq=max_seq))
    pf = serve.get_deployment_handle(f"{name}-prefill")
    dc = serve.get_deployment_handle(f"{name}-decode")
    orch_dep = serve.deployment(PDOrchestrator, name=name)
    serve.run(orch_dep.bind(pf, dc))
    return serve.get_deployment_handle(name)
