"""Public API (reference surface: python/ray/_private/worker.py:1331 ray.init,
:2726 get, :2879 put, :2944 wait; remote_function.py:314 @ray.remote).

``init()`` with no address starts a single-node cluster in-process (GCS +
raylet on the shared IO loop; workers are real child processes).
``init(address="host:port")`` connects to an existing cluster's GCS.
"""

from __future__ import annotations

import atexit
import functools
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu.common.config import GLOBAL_CONFIG
from ray_tpu.common.ids import ActorID, NodeID
from ray_tpu.core_worker.actor import (
    ActorClass,
    ActorHandle,
    _resources_from_options,
    _strategy_from_options,
)
from ray_tpu.core_worker.generator import ObjectRefGenerator
from ray_tpu.core_worker.reference import ObjectRef

logger = logging.getLogger(__name__)

_global_lock = threading.RLock()
_head: Optional[dict] = None  # {"gcs": GcsServer, "raylet": Raylet} when we started them
_client = None  # ClientContext when connected via ray://


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    system_config: Optional[dict] = None,
    job_name: str = "",
    runtime_env: Optional[dict] = None,
    dashboard: bool = False,
    dashboard_port: int = 0,
) -> dict:
    """Start or connect. Returns {"gcs_address": (host, port), "node_id": hex}.

    With no ``address``, ``RT_ADDRESS`` (exported by the job supervisor for
    submitted drivers — reference: RAY_ADDRESS) connects to the running
    cluster; a submitted job's runtime env becomes the driver's job-level
    default via ``RT_JOB_RUNTIME_ENV``.
    """
    global _head, _client
    import json as _json
    import os as _os

    from ray_tpu.core_worker.worker import MODE_DRIVER, CoreWorker

    if address is None:
        address = _os.environ.get("RT_ADDRESS")
    if address is not None and address.startswith("ray://"):
        # Client mode (reference ray://): the driver lives OUTSIDE the
        # cluster network and speaks only to the head's client server;
        # a server-side session driver proxies the whole API. runtime_env
        # ships to the session driver as its job default; node-shape args
        # are meaningless off-cluster and rejected loudly.
        unsupported = {"num_cpus": num_cpus, "num_tpus": num_tpus,
                       "resources": resources, "labels": labels,
                       "system_config": system_config}
        bad = [k for k, v in unsupported.items() if v]
        if bad or dashboard:
            raise ValueError(
                f"init(address='ray://...') does not accept {bad or ['dashboard']}: "
                "these configure a NODE; a ray:// client joins no node")
        from ray_tpu.client.client import ClientContext

        host, _, port = address[len("ray://"):].partition(":")
        with _global_lock:
            if _client is not None or CoreWorker._current is not None:
                raise RuntimeError(
                    "ray_tpu.init() already called; call shutdown() first")
            _client = ClientContext(host, int(port),
                                    runtime_env=runtime_env)
        return {"client": True, "address": address}
    if runtime_env is None and _os.environ.get("RT_JOB_RUNTIME_ENV"):
        runtime_env = _json.loads(_os.environ["RT_JOB_RUNTIME_ENV"])
    if runtime_env:
        # validate BEFORE any daemon starts: failing after GcsServer/Raylet
        # are up would leak a running head the caller can't shut down
        from ray_tpu.runtime_env.runtime_env import validate as _validate_env

        _validate_env(runtime_env)
    with _global_lock:
        if CoreWorker._current is not None:
            raise RuntimeError("ray_tpu.init() already called; call shutdown() first")
        if system_config:
            GLOBAL_CONFIG.initialize(system_config)
            GLOBAL_CONFIG.reset_cache()
        if address is None:
            node_resources = dict(resources or {})
            node_labels = dict(labels or {})
            if num_cpus is not None:
                node_resources["CPU"] = num_cpus
            if num_tpus is not None:
                node_resources["TPU"] = num_tpus
            elif "TPU" not in node_resources:
                # metadata autodetection + slice labels (reference
                # accelerators/tpu.py:16-30,338-374): SLICE_PACK placement
                # works without hand-set num_tpus
                from ray_tpu.common.resources import (
                    LABEL_SLICE_NAME, LABEL_SLICE_TOPOLOGY,
                    LABEL_SLICE_WORKER_INDEX)
                from ray_tpu.common.tpu_detect import detect

                found = detect()
                node_resources["TPU"] = found["chips"]
                if found["topology"]:
                    node_labels.setdefault(
                        LABEL_SLICE_TOPOLOGY, str(found["topology"]))
                if found["slice_name"]:
                    node_labels.setdefault(
                        LABEL_SLICE_NAME, str(found["slice_name"]))
                if found["worker_id"] is not None:
                    node_labels.setdefault(
                        LABEL_SLICE_WORKER_INDEX, str(found["worker_id"]))
            if GLOBAL_CONFIG.get("control_plane_procs"):
                # Multi-process deployment shape (control_plane.py): the
                # GCS server and the raylet each get their OWN process —
                # own loop, own GIL — and the driver talks to them over
                # the ordinary rpc layer. Control-plane scheduling no
                # longer time-slices against driver submit/reply work.
                from ray_tpu.control_plane import ProcHead

                head = ProcHead(
                    resources=node_resources, labels=node_labels,
                    system_config=GLOBAL_CONFIG.system_config_json())
                _head = {"proc_head": head,
                         "session_dir": head.session_dir,
                         "node_id": head.node_id}
                gcs_address = head.gcs_address
                raylet_address = head.raylet_address
                node_id = head.node_id
            else:
                from ray_tpu.gcs.server import GcsServer
                from ray_tpu.raylet.raylet import Raylet

                gcs = GcsServer()
                gcs.start()
                raylet = Raylet(gcs.address, resources=node_resources,
                                labels=node_labels)
                # before start(): the node's own ALIVE registration must
                # land in the export log too
                gcs.attach_export_logger(raylet.session_dir)
                raylet.start()
                _head = {"gcs": gcs, "raylet": raylet,
                         "session_dir": raylet.session_dir,
                         "node_id": raylet.node_id}
                gcs_address = gcs.address
                raylet_address = raylet.server.address
                node_id = raylet.node_id
        else:
            host, _, port = address.partition(":")
            gcs_address = (host, int(port))
            from ray_tpu.gcs.client import GcsClient

            probe = GcsClient(gcs_address)
            nodes_info = probe.get_all_nodes()
            probe.close()
            alive = [n for n in nodes_info if n["alive"]]
            if not alive:
                raise ConnectionError(f"no alive nodes in cluster at {address}")
            raylet_address = tuple(alive[0]["address"])
            node_id = NodeID(alive[0]["node_id"])

        try:
            cw = CoreWorker(
                mode=MODE_DRIVER,
                gcs_address=gcs_address,
                raylet_address=raylet_address,
                node_id=node_id,
            )
            cw.job_runtime_env = dict(runtime_env) if runtime_env else None
            if _head is not None and _head.get("proc_head") is not None:
                # supervisor → core worker: a dead GCS/raylet process
                # fails new control-plane work with a typed error instead
                # of hanging
                _head["proc_head"].set_on_death(cw.fail_control_plane)
            if GLOBAL_CONFIG.get("log_to_driver"):
                _subscribe_worker_logs(cw)
            atexit.register(_shutdown_atexit)
            out = {"gcs_address": gcs_address, "node_id": node_id.hex()}
            if dashboard and _head is not None:
                from ray_tpu.dashboard import Dashboard

                dash = Dashboard(gcs_address, _head["session_dir"],
                                 port=dashboard_port)
                dash.start()
                _head["dashboard"] = dash
                out["dashboard_url"] = dash.url
            return out
        except BaseException:
            # a failure after the head came up must not leak it — in the
            # multi-process shape that would orphan two OS daemons (and
            # the raylet's workers) with no supervisor
            if _head is not None:
                if _head.get("proc_head") is not None:
                    _head["proc_head"].stop()
                else:
                    _head["raylet"].stop()
                    _head["gcs"].stop()
                _head = None
            if CoreWorker._current is not None:
                CoreWorker._current.shutdown()
            raise


def _subscribe_worker_logs(cw) -> None:
    """Print worker stdout/stderr lines this job produced, ``(pid=…)``
    prefixed (reference log_monitor.py → driver UX)."""
    import sys as _sys

    my_job = cw.job_id.hex()

    def on_log(_key, msg):
        if msg.get("job_id") not in ("", my_job):
            return  # another driver's workers
        name = msg.get("actor_name") or ""
        tag = (f"{name} pid={msg.get('pid')}" if name
               else f"pid={msg.get('pid')}")
        out = _sys.stderr if msg.get("stream") == "stderr" else _sys.stdout
        for line in msg.get("lines", []):
            print(f"({tag}) {line}", file=out)

    try:
        cw.gcs.subscriber.subscribe("worker_log", on_log)
    except Exception:  # noqa: BLE001 — log relay is best-effort
        logger.debug("worker-log subscription failed", exc_info=True)


def _shutdown_atexit():
    try:
        shutdown()
    except Exception:  # noqa: BLE001
        pass


def shutdown() -> None:
    global _head, _client
    from ray_tpu.core_worker.worker import CoreWorker

    with _global_lock:
        if _client is not None:
            _client.disconnect()
            _client = None
            return
        cw = CoreWorker._current
        if cw is not None:
            if _head is not None and getattr(cw, "_control_plane_error",
                                             None) is None:
                # before cw.shutdown(): the report snapshots cluster
                # shape through the still-live core worker (skipped when
                # the control plane is already dead — nothing to snapshot)
                from ray_tpu.util import usage

                usage.write_report(_head["session_dir"])
            if getattr(cw, "_control_plane_error", None) is None:
                try:
                    cw.gcs.finish_job(cw.job_id)
                except Exception:  # noqa: BLE001
                    pass
            cw.shutdown()
        if _head is not None:
            node_id = _head["node_id"]
            if _head.get("dashboard") is not None:
                _head["dashboard"].stop()
            if _head.get("proc_head") is not None:
                _head["proc_head"].stop()  # raylet first, then GCS + shm
            else:
                _head["raylet"].stop()
                _head["gcs"].stop()
                from ray_tpu.object_store.shm import node_shm_name
                from ray_tpu.object_store.shm import unlink as shm_unlink

                shm_unlink(node_shm_name(node_id))
            # reap spill state orphaned by DEAD processes (crashed
            # sessions, SIGKILLed workers): stale rt_spill_*/
            # rtshm_spill_* dirs and .tmp.<pid> write fragments. Live
            # sessions sharing the dir are untouched (pid / segment
            # liveness checks).
            try:
                from ray_tpu.object_store.shm import gc_spill_dirs

                gc_spill_dirs()
            except Exception:  # noqa: BLE001 — shutdown is best-effort
                pass
            # same sweep for transfer-service scratch: half-landed arena
            # allocations (.pull.<pid> markers) whose puller process died
            # mid-download are aborted so their spans don't pin the arena
            try:
                from ray_tpu.object_store.transfer import gc_transfer_scratch

                gc_transfer_scratch()
            except Exception:  # noqa: BLE001 — shutdown is best-effort
                pass
            _head = None


def is_initialized() -> bool:
    from ray_tpu.core_worker.worker import CoreWorker

    return CoreWorker._current is not None or _client is not None


def _core_worker():
    from ray_tpu.core_worker.worker import CoreWorker

    return CoreWorker.current_or_raise()


# ----------------------------------------------------------------- remote API

class RemoteFunction:
    def __init__(self, fn, default_options: Optional[dict] = None):
        self._fn = fn
        self._options = default_options or {}
        functools.update_wrapper(self, fn)
        self._serialized = None

    def remote(self, *args, **kwargs):
        return self._invoke(args, kwargs, self._options)

    def options(self, **opts):
        merged = dict(self._options)
        merged.update(opts)
        return _RemoteFunctionOptions(self, merged)

    def bind(self, *args, **kwargs):
        from ray_tpu.graph.dag import FunctionNode

        return FunctionNode(self, args, kwargs, self._options)

    def _invoke(self, args, kwargs, opts):
        import cloudpickle

        if _client is not None:
            # defined before init("ray://...") (the normal import-time
            # decorator pattern): dispatch to the client at CALL time
            from ray_tpu.client.client import ClientRemoteFunction

            return ClientRemoteFunction(
                self._fn, _client, opts).remote(*args, **kwargs)
        cw = _core_worker()
        if self._serialized is None:
            self._serialized = cloudpickle.dumps(self._fn)
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        refs = cw.submit_task(
            self._fn,
            args,
            kwargs,
            num_returns=0 if streaming else num_returns,
            streaming=streaming,
            resources=_resources_from_options(opts),
            label_selector=opts.get("label_selector"),
            scheduling_strategy=_strategy_from_options(opts),
            max_retries=opts.get("max_retries"),
            name=opts.get("name", self._fn.__name__),
            serialized_func=self._serialized,
            runtime_env=opts.get("runtime_env"),
        )
        if streaming:
            return refs  # an ObjectRefGenerator
        if num_returns == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._fn.__name__!r} cannot be called directly; "
            f"use .remote()")


class _RemoteFunctionOptions:
    def __init__(self, rf: RemoteFunction, opts: dict):
        self._rf = rf
        self._opts = opts

    def remote(self, *args, **kwargs):
        return self._rf._invoke(args, kwargs, self._opts)

    def bind(self, *args, **kwargs):
        from ray_tpu.graph.dag import FunctionNode

        return FunctionNode(self._rf, args, kwargs, self._opts)


def remote(*args, **options):
    """``@remote`` / ``@remote(num_cpus=..., num_tpus=..., ...)`` on functions
    and classes."""
    if _client is not None:
        from ray_tpu.client.client import (ClientActorClass,
                                           ClientRemoteFunction)

        def client_wrap(target):
            if isinstance(target, type):
                return ClientActorClass(target, _client, options)
            return ClientRemoteFunction(target, _client, options)

        if len(args) == 1 and callable(args[0]) and not options:
            return client_wrap(args[0])
        if args:
            raise TypeError("@remote takes keyword options only")
        return client_wrap
    if len(args) == 1 and callable(args[0]) and not options:
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target)
        return RemoteFunction(target)
    if args:
        raise TypeError("@remote takes keyword options only")

    def wrap(target):
        if isinstance(target, type):
            return ActorClass(target, options)
        return RemoteFunction(target, options)

    return wrap


def method(**opts):
    """Decorator for actor methods (num_returns)."""

    def wrap(fn):
        fn.__rt_method_opts__ = opts
        return fn

    return wrap


# -------------------------------------------------------------------- core ops

def put(value: Any, *, _tensor_transport: Optional[str] = None):
    """Store an object. ``_tensor_transport="device"`` keeps jax.Array
    leaves resident in this process's device HBM and ships only a
    marker; consumers on other workers pull the tensors out-of-band
    (reference: experimental/gpu_object_manager 'RDT')."""
    if _client is not None:
        if _tensor_transport is not None:
            raise NotImplementedError(
                "_tensor_transport is not supported in ray:// client mode "
                "(the client process has no cluster-visible device store)")
        return _client.put(value)
    return _put_local(value, _tensor_transport)


def _put_local(value: Any, tensor_transport: Optional[str] = None) -> ObjectRef:
    return _core_worker().put(value, tensor_transport=tensor_transport)


def get(refs, *, timeout: Optional[float] = None):
    if _client is not None:
        return _client.get(refs, timeout=timeout)
    single = isinstance(refs, ObjectRef)
    ref_list = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
    values = _core_worker().get(ref_list, timeout)
    return values[0] if single else values


async def get_async(ref: "ObjectRef", *, timeout: Optional[float] = None):
    """Awaitable single-ref ``get`` for async actors and event-loop code:
    resolves on the calling loop with no executor thread parked on a
    condition variable (reference: ``await object_ref`` / CoreWorker
    GetAsync).  Not available in ray:// client mode."""
    if _client is not None:
        raise NotImplementedError(
            "get_async is not supported in ray:// client mode")
    if not isinstance(ref, ObjectRef):
        raise TypeError(f"get_async() expects an ObjectRef, got {type(ref)}")
    return await _core_worker().get_async(ref, timeout)


def wait(refs, *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if _client is not None:
        return _client.wait(list(refs), num_returns=num_returns,
                            timeout=timeout)
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return _core_worker().wait(list(refs), num_returns, timeout, fetch_local)


def kill(actor, *, no_restart: bool = True):
    if _client is not None:
        return _client.kill(actor, no_restart=no_restart)
    _core_worker().kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False):
    """Cancel the task that produces ``ref`` (reference:
    python/ray/_private/worker.py cancel → CoreWorker::CancelTask).

    Best-effort: a queued task is removed and its refs resolve to
    :class:`TaskCancelledError`; a running sync task gets the error raised
    asynchronously in its thread (blocking C calls need ``force``); a
    running ``async def`` actor call is asyncio-cancelled; a streaming
    generator stops at its next yield. ``force=True`` kills the executing
    worker process. Already-finished tasks are unaffected."""
    if _client is not None:
        return _client.cancel(ref, force=force)
    return _core_worker().cancel_task(ref, force=force)


def get_actor(name: str, namespace: str = "default"):
    if _client is not None:
        return _client.get_actor(name, namespace)
    info = _core_worker().gcs.get_actor_by_name(name, namespace)
    if info is None or info["state"] == "DEAD":
        raise ValueError(f"no alive actor named {name!r}")
    return ActorHandle(ActorID.from_hex(info["actor_id"]))


# ----------------------------------------------------------------- inspection

def nodes() -> List[dict]:
    if _client is not None:
        return _client.nodes()
    out = []
    for n in _core_worker().gcs.get_all_nodes():
        out.append({
            "NodeID": NodeID(n["node_id"]).hex(),
            "Alive": n["alive"],
            "Address": n["address"],
            "Resources": n["resources"]["total"],
            "Available": n["resources"]["available"],
            "Labels": n["resources"]["labels"],
        })
    return out


def cluster_resources() -> Dict[str, float]:
    if _client is not None:
        return _client.cluster_resources()
    return _core_worker().gcs.cluster_resources()["total"]


def available_resources() -> Dict[str, float]:
    if _client is not None:
        return _client.available_resources()
    return _core_worker().gcs.cluster_resources()["available"]


def timeline() -> List[dict]:
    """Chrome-trace events for completed tasks (reference: ray.timeline)."""
    return _core_worker().gcs.call("get_task_events")


class RuntimeContext:
    def __init__(self, cw):
        self._cw = cw

    @property
    def job_id(self):
        return self._cw.job_id

    @property
    def node_id(self):
        return self._cw.node_id

    @property
    def worker_id(self):
        return self._cw.worker_id

    def get_task_id(self):
        return self._cw.current_task_id()

    def get_actor_id(self):
        return self._cw._actor_id

    @property
    def gcs_address(self):
        return self._cw.gcs_address


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_core_worker())
