"""Logical-axis sharding rules.

Model code annotates arrays with *logical* axis names ("embed", "heads",
"batch", …); a :class:`ShardingRules` table maps those to mesh axes. Swapping
the table re-lays-out the whole model (fsdp vs tp vs both) without touching
model code. This replaces the reference's per-framework process-group plumbing
(torch DDP/FSDP wiring in reference ``python/ray/train/torch/config.py``) with
a declarative, compiler-visible scheme.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

LOGICAL_AXES = (
    "batch",      # global batch            → dp + fsdp
    "seq",        # sequence (activations)  → sp
    "embed",      # model dim
    "heads",      # attention heads         → tp
    "kv_heads",   # kv heads (GQA)
    "head_dim",
    "mlp",        # ffn hidden              → tp
    "vocab",      # logits vocab            → tp
    "embed_vocab",  # embedding-table vocab dim (gather axis) → replicated
    "layers",     # scan-over-layers leading axis (never sharded)
    "expert",     # MoE experts             → ep (fsdp, sp)
    "tokens",     # flattened batch·seq (MoE routing) → dp + fsdp + sp
    "kv_seq",     # kv-cache sequence dim
    None,
)

Axis = Union[str, Tuple[str, ...], None]


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """logical axis → mesh axis (or tuple of mesh axes, or None=replicate)."""

    batch: Axis = ("dp", "fsdp")
    seq: Axis = "sp"
    embed: Axis = None
    embed_fsdp: Axis = "fsdp"   # weight-matrix embed dim: sharded for ZeRO-3
    heads: Axis = "tp"
    kv_heads: Axis = "tp"
    head_dim: Axis = None
    mlp: Axis = "tp"
    vocab: Axis = "tp"
    embed_vocab: Axis = None
    layers: Axis = None
    expert: Axis = ("fsdp", "sp")
    # flattened (batch·seq) token dim: the merge of the batch and seq
    # layouts, so reshape (B,S,…)→(T,…) preserves the sharding exactly
    tokens: Axis = ("dp", "fsdp", "sp")
    kv_seq: Axis = None

    def mesh_axes(self, logical_axes: Sequence[Optional[str]]):
        out = []
        used = set()
        for ax in logical_axes:
            m = getattr(self, ax) if ax is not None else None
            # A mesh axis may appear at most once in a PartitionSpec; later
            # occurrences replicate (e.g. embed_fsdp when tp==fsdp axis reuse).
            if m is not None:
                flat = (m,) if isinstance(m, str) else tuple(m)
                flat = tuple(a for a in flat if a not in used)
                used.update(flat)
                m = None if not flat else (flat[0] if len(flat) == 1 else flat)
            out.append(m)
        return tuple(out)


# Default rule tables for common regimes.
FSDP_RULES = ShardingRules(heads=None, kv_heads=None, mlp="fsdp", vocab=None,
                           embed_fsdp="fsdp")
TP_RULES = ShardingRules(embed_fsdp=None)
FSDP_TP_RULES = ShardingRules()


def _ensure_partitionable_rng() -> None:
    """jax < 0.5 defaults ``jax_threefry_partitionable`` to False, under
    which a jitted init whose output is sharded along an array's LEADING
    dim generates different random bits than the unsharded computation
    (measured on jax 0.4.37: ``truncated_normal`` under
    ``out_shardings=P("fsdp", None)`` diverges; trailing-dim sharding does
    not).  That breaks the sharded-from-birth contract — "same seed ⇒ same
    params as single-device" — for any weight whose dim 0 is sharded
    (e.g. llama's ``lm_head`` under ZeRO-3 rules).  jax >= 0.5 flips the
    default to True; align older versions with the modern semantics."""
    import jax

    try:
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
        if (major, minor) >= (0, 5):
            return
        jax.config.update("jax_threefry_partitionable", True)
    except Exception:  # noqa: BLE001 — unknown version string: leave as-is
        pass


# At import, not per-call: the flag must flip BEFORE any RNG value that
# will later be compared against a sharded computation is drawn — the
# stream itself changes, so a mid-session flip would split one process
# into two incompatible RNG regimes.
_ensure_partitionable_rng()


def set_mesh(mesh):
    """Context manager activating ``mesh`` for jitted computations.

    Compat shim: jax >= 0.5 exposes ``jax.set_mesh`` (populates the
    abstract mesh that ``with_logical_constraint`` reads); older
    releases only have the legacy ``with mesh:`` context, which the
    constraint path also honors — callers use this instead of either
    spelling so the same test/model code runs on both.
    """
    import jax

    setter = getattr(jax, "set_mesh", None) \
        or getattr(jax.sharding, "set_mesh", None)
    if setter is not None:
        return setter(mesh)
    return mesh  # legacy: Mesh is itself a context manager


def logical_spec(logical_axes: Sequence[Optional[str]],
                 rules: ShardingRules):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*rules.mesh_axes(logical_axes))


def logical_sharding(logical_axes, mesh, rules: ShardingRules):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, logical_spec(logical_axes, rules))


def with_logical_constraint(x, logical_axes, rules: ShardingRules):
    """`lax.with_sharding_constraint` by logical axis names (inside jit).

    No-op when no mesh is active (single-device eager/jit use), and mesh
    axes the active mesh doesn't have are dropped — the same model code runs
    unsharded, dp-only, or fully fsdp+tp+sp without edits.
    """
    import jax

    # jax >= 0.5 exposes the abstract mesh; on older releases only the
    # legacy `with mesh:` context exists — fall through to it.
    get_abstract = getattr(jax.sharding, "get_abstract_mesh", None)
    mesh = get_abstract() if get_abstract is not None else None
    legacy_mesh = None
    if mesh is None or mesh.empty:
        # A legacy `with mesh:` context doesn't populate the abstract mesh;
        # honor it rather than silently dropping the constraint.
        from jax._src import mesh as mesh_lib

        legacy_mesh = mesh_lib.thread_resources.env.physical_mesh
        if legacy_mesh.empty:
            return x
        mesh = legacy_mesh
    names = set(mesh.axis_names)

    def keep(ax):
        if ax is None:
            return None
        if isinstance(ax, tuple):
            kept = tuple(a for a in ax if a in names)
            return None if not kept else (kept[0] if len(kept) == 1 else kept)
        return ax if ax in names else None

    spec = jax.sharding.PartitionSpec(
        *(keep(a) for a in rules.mesh_axes(logical_axes)))
    if legacy_mesh is not None:
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(legacy_mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)


def shard_pytree(tree, axes_tree, mesh, rules: ShardingRules):
    """Place every leaf of ``tree`` per its logical axes in ``axes_tree``.

    ``axes_tree`` has the same structure with tuples of logical axis names
    (or None leaves = fully replicated).
    """
    import jax

    def place(axes, x):
        sh = logical_sharding(axes or (None,) * getattr(x, "ndim", 0),
                              mesh, rules)
        return jax.device_put(x, sh)

    # Map over axes_tree first so its tuple leaves are treated as leaves.
    return jax.tree.map(place, axes_tree, tree,
                        is_leaf=lambda t: t is None or isinstance(t, tuple))
