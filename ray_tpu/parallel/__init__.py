"""ray_tpu.parallel — mesh construction and sharding for TPU pods.

The reference framework delegates model parallelism to torch/NCCL (SURVEY.md
§2.3: DP via torch DDP in ``python/ray/train/torch/config.py:66-151``, TP/PP
only through vLLM passthrough). Here parallelism is a first-class, in-framework
concern: a named :class:`jax.sharding.Mesh` over the pod slice, logical-axis
sharding rules, and helpers that place pytrees onto the mesh. XLA inserts the
ICI collectives; multi-slice meshes put the outermost (data) axis on DCN.
"""

from ray_tpu.parallel.mesh import (
    MeshConfig,
    best_effort_mesh,
    get_abstract_mesh,
    make_mesh,
    mesh_shape_for,
    stage_device_slices,
)
from ray_tpu.parallel.sharding import (
    LOGICAL_AXES,
    ShardingRules,
    logical_sharding,
    logical_spec,
    shard_pytree,
    with_logical_constraint,
)

__all__ = [
    "MeshConfig",
    "ShardingRules",
    "LOGICAL_AXES",
    "best_effort_mesh",
    "get_abstract_mesh",
    "logical_sharding",
    "logical_spec",
    "make_mesh",
    "mesh_shape_for",
    "stage_device_slices",
    "shard_pytree",
    "with_logical_constraint",
]
