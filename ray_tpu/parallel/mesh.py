"""Device mesh construction.

Axis convention (outer → inner, slowest → fastest varying):

    ``dp``    pure data parallel; gradients all-reduced. Safe to map onto DCN
              (multi-slice) because it communicates once per step.
    ``fsdp``  data parallel with parameter/optimizer sharding (ZeRO-3 style);
              all-gathers weights per layer → must ride ICI.
    ``sp``    sequence/context parallel (ring attention / all-to-all); ICI.
    ``tp``    tensor parallel (megatron-style activation collectives); the
              chattiest axis → innermost, nearest-neighbor ICI.
    ``ep``    expert parallel for MoE, aliased over fsdp×sp in the flat mesh.
    ``pp``    pipeline stages (between-stage ppermute).

The reference framework has no in-framework notion of any of these (SURVEY.md
§2.3 "Parallelism strategies"); its TPU support stops at advertising a
``TPU-<pod>-head`` custom resource (reference ``python/ray/_private/
accelerators/tpu.py:338-374``). Here the mesh IS the programming model.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Degrees for each parallelism axis. ``-1`` on at most one axis means
    "absorb all remaining devices"."""

    dp: int = 1
    fsdp: int = -1
    sp: int = 1
    tp: int = 1
    pp: int = 1
    # Number of ICI-connected slices; >1 puts the leading dp axis on DCN.
    num_slices: int = 1

    def resolve(self, n_devices: int) -> dict:
        sizes = {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
                 "sp": self.sp, "tp": self.tp}
        wildcards = [a for a, s in sizes.items() if s == -1]
        if len(wildcards) > 1:
            raise ValueError(f"at most one axis may be -1, got {wildcards}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcards:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wildcards[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def mesh_shape_for(n_devices: int, config: Optional[MeshConfig] = None):
    config = config or MeshConfig()
    sizes = config.resolve(n_devices)
    return tuple(sizes[a] for a in AXIS_ORDER)


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None,
              axis_names: Sequence[str] = AXIS_ORDER):
    """Build a `jax.sharding.Mesh` over ``devices`` (default: all).

    Uses `mesh_utils.create_device_mesh` so axis order maps onto the physical
    ICI torus (innermost axes = nearest neighbors); for ``num_slices > 1``
    uses the hybrid helper so the outer dp axis crosses DCN.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in axis_names)
    if config.num_slices > 1:
        if sizes["dp"] % config.num_slices:
            raise ValueError("dp degree must be a multiple of num_slices")
        per_slice = list(shape)
        dp_i = list(axis_names).index("dp")
        per_slice[dp_i] = sizes["dp"] // config.num_slices
        dcn = [1] * len(shape)
        dcn[dp_i] = config.num_slices
        dev_array = mesh_utils.create_hybrid_device_mesh(
            tuple(per_slice), tuple(dcn), devices=devices)
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except (ValueError, AssertionError):
            # Non-torus device sets (CPU virtual devices, odd subsets).
            import numpy as np
            if devices and getattr(devices[0], "platform", "") == "tpu":
                import warnings
                warnings.warn(
                    "create_device_mesh failed on TPU devices; falling back "
                    "to a topology-oblivious reshape — tp/sp collectives may "
                    "cross non-neighbor ICI links", stacklevel=2)
            dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def best_effort_mesh(tp: int = 1, sp: int = 1, devices=None):
    """Mesh that uses all devices: requested tp/sp, remainder on fsdp."""
    import jax
    n = len(devices) if devices is not None else len(jax.devices())
    tp = math.gcd(tp, n)
    sp = math.gcd(sp, max(1, n // tp))
    return make_mesh(MeshConfig(fsdp=-1, sp=sp, tp=tp), devices=devices)


def stage_device_slices(n_stages: int, devices: Optional[Sequence] = None):
    """Partition ``devices`` into ``n_stages`` contiguous equal slices —
    the per-stage device placement for MPMD pipeline parallelism
    (train/pipeline.py).  Contiguity matters on real hardware: the pp
    axis is outermost in :data:`AXIS_ORDER`, so a contiguous slice of the
    device list is an ICI-local neighborhood and the only inter-slice
    traffic is the stage boundary activation/grad hop."""
    import jax

    devices = list(devices if devices is not None else jax.devices())
    if n_stages < 1:
        raise ValueError(f"n_stages must be >= 1, got {n_stages}")
    if len(devices) % n_stages:
        raise ValueError(
            f"{len(devices)} devices not divisible into {n_stages} equal "
            f"stage slices")
    per = len(devices) // n_stages
    return [devices[i * per:(i + 1) * per] for i in range(n_stages)]


def get_abstract_mesh(n_devices: int, config: Optional[MeshConfig] = None,
                      axis_names: Sequence[str] = AXIS_ORDER):
    """An AbstractMesh for shape/sharding reasoning without real devices."""
    from jax.sharding import AbstractMesh

    config = config or MeshConfig()
    sizes = config.resolve(n_devices)
    return AbstractMesh(tuple(sizes[a] for a in axis_names), tuple(axis_names))
