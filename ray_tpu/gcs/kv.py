"""Internal key-value store (reference: gcs/gcs_server/gcs_kv_manager.h).

Namespaced binary KV used for: collective group rendezvous, named actors,
function table, cluster metadata.  In-memory with an optional JSON-lines
append log for GCS restart recovery (the reference's Redis-backed fault
tolerance, store_client/redis_store_client.h, is modeled as a flush/replay
file since Redis isn't part of this image).
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, List, Optional


class InternalKV:
    def __init__(self, persist_path: Optional[str] = None):
        self._data: Dict[bytes, bytes] = {}
        self._lock = threading.Lock()
        self._persist_path = persist_path
        self._log = None
        if persist_path:
            if os.path.exists(persist_path):
                self._replay(persist_path)
            self._log = open(persist_path, "ab")

    def _replay(self, path: str):
        with open(path, "rb") as f:
            while True:
                try:
                    op, key, value = pickle.load(f)
                except EOFError:
                    break
                if op == "put":
                    self._data[key] = value
                elif op == "del":
                    self._data.pop(key, None)

    def _append(self, op: str, key: bytes, value: Optional[bytes]):
        if self._log is not None:
            pickle.dump((op, key, value), self._log)
            self._log.flush()

    @staticmethod
    def _k(namespace: str, key: bytes | str) -> bytes:
        if isinstance(key, str):
            key = key.encode()
        return namespace.encode() + b"\x00" + key

    def put(self, namespace: str, key, value: bytes, overwrite: bool = True) -> bool:
        k = self._k(namespace, key)
        with self._lock:
            if not overwrite and k in self._data:
                return False
            self._data[k] = value
            self._append("put", k, value)
            return True

    def get(self, namespace: str, key) -> Optional[bytes]:
        with self._lock:
            return self._data.get(self._k(namespace, key))

    def exists(self, namespace: str, key) -> bool:
        with self._lock:
            return self._k(namespace, key) in self._data

    def delete(self, namespace: str, key) -> bool:
        k = self._k(namespace, key)
        with self._lock:
            existed = self._data.pop(k, None) is not None
            if existed:
                self._append("del", k, None)
            return existed

    def keys(self, namespace: str, prefix: bytes | str = b"") -> List[bytes]:
        p = self._k(namespace, prefix)
        ns_len = len(namespace.encode()) + 1
        with self._lock:
            return [k[ns_len:] for k in self._data if k.startswith(p)]

    def close(self):
        if self._log is not None:
            self._log.close()
            self._log = None
