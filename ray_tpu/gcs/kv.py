"""Internal key-value store (reference: gcs/gcs_server/gcs_kv_manager.h).

Namespaced binary KV used for: collective group rendezvous, named actors,
function table, cluster metadata.  In-memory, with optional durability
delegated to :class:`~ray_tpu.gcs.storage.GcsTableStorage` (one "kv" table
in the shared GCS table log machinery) — the reference's Redis-backed fault
tolerance (store_client/redis_store_client.h) modeled as replay-on-restart,
with torn-tail tolerance and compaction inherited from the table store.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class InternalKV:
    def __init__(self, storage=None, *, owns_storage: bool = False):
        """``storage`` is a :class:`GcsTableStorage` (usually the GCS
        server's own, shared) whose "kv" table backs this store; None keeps
        the KV purely in-memory. The storage is only closed here when this
        KV created it (``owns_storage``)."""
        self._data: Dict[bytes, bytes] = {}
        self._lock = threading.Lock()
        self._storage = storage
        self._owns_storage = owns_storage
        if storage is not None:
            for key, rec in storage.all("kv").items():
                self._data[key] = rec["v"]

    @staticmethod
    def _k(namespace: str, key: bytes | str) -> bytes:
        if isinstance(key, str):
            key = key.encode()
        return namespace.encode() + b"\x00" + key

    def put(self, namespace: str, key, value: bytes, overwrite: bool = True) -> bool:
        k = self._k(namespace, key)
        with self._lock:
            if not overwrite and k in self._data:
                return False
            self._data[k] = value
            if self._storage is not None:
                self._storage.put("kv", k, {"v": value})
            return True

    def get(self, namespace: str, key) -> Optional[bytes]:
        with self._lock:
            return self._data.get(self._k(namespace, key))

    def exists(self, namespace: str, key) -> bool:
        with self._lock:
            return self._k(namespace, key) in self._data

    def delete(self, namespace: str, key) -> bool:
        k = self._k(namespace, key)
        with self._lock:
            existed = self._data.pop(k, None) is not None
            if existed and self._storage is not None:
                self._storage.delete("kv", k)
            return existed

    def keys(self, namespace: str, prefix: bytes | str = b"") -> List[bytes]:
        p = self._k(namespace, prefix)
        ns_len = len(namespace.encode()) + 1
        with self._lock:
            return [k[ns_len:] for k in self._data if k.startswith(p)]

    def close(self):
        if self._storage is not None and self._owns_storage:
            self._storage.close()
        self._storage = None
