"""GCS — the cluster control plane.

Equivalent of the reference's GCS server (src/ray/gcs/gcs_server/gcs_server.cc
and its managers): node registry + health checking, aggregated resource view,
job table, actor lifecycle management with restart-on-failure, placement
groups with two-phase commit across raylets, internal KV, and a task-event
store.  Data-plane state (object VALUES) is deliberately NOT here — ownership
lives with workers, as in the reference.  The GCS does keep the object
LOCATION directory (which nodes hold a copy, arena or spilled — reference:
the owner-reported object directory): owners push coalesced add/remove/spill
batches and cold ``get`` paths resolve holders here before riding the
node-to-node transfer service.

State changes are published on pubsub channels: "node", "actor", "pg", "job",
"resources", "object_loc".
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu.common.config import GLOBAL_CONFIG
from ray_tpu.common.ids import ActorID, JobID, NodeID, PlacementGroupID, WorkerID
from ray_tpu.common.resources import NodeResources, ResourceRequest
from ray_tpu.rpc.pubsub import Publisher
from ray_tpu.rpc.rpc import (IoContext, RetryableRpcClient, RpcClient,
                             RpcServer)
from ray_tpu.scheduling import ClusterView, NodeEntry, policies

logger = logging.getLogger(__name__)

# Actor lifecycle states (reference protocol: gcs_actor_manager.h:300-332)
ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"

PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_REMOVED = "REMOVED"
PG_RESCHEDULING = "RESCHEDULING"


@dataclass
class ActorRecord:
    actor_id: ActorID
    job_id: JobID
    name: Optional[str]
    creation_spec: bytes  # pickled TaskSpec for the creation task
    max_restarts: int
    namespace: str = "default"
    state: str = ACTOR_PENDING
    node_id: Optional[NodeID] = None
    worker_id: Optional[WorkerID] = None
    address: Optional[Tuple[str, int]] = None
    # C fastloop dispatch port of the hosting worker (rpc/native/fastloop.c);
    # None when the worker runs without the native loop
    fast_port: Optional[int] = None
    num_restarts: int = 0
    death_cause: str = ""
    handled_deaths: set = field(default_factory=set)

    def to_store(self) -> dict:
        return {
            "actor_id": self.actor_id.binary(),
            "job_id": self.job_id.binary(),
            "name": self.name,
            "namespace": self.namespace,
            "creation_spec": self.creation_spec,
            "max_restarts": self.max_restarts,
            "state": self.state,
            "node_id": self.node_id and self.node_id.binary(),
            "worker_id": self.worker_id and self.worker_id.binary(),
            "address": self.address,
            "fast_port": self.fast_port,
            "num_restarts": self.num_restarts,
            "death_cause": self.death_cause,
            "handled_deaths": [w.binary() for w in self.handled_deaths],
        }

    @classmethod
    def from_store(cls, d: dict) -> "ActorRecord":
        return cls(
            actor_id=ActorID(d["actor_id"]),
            job_id=JobID(d["job_id"]),
            name=d["name"],
            namespace=d.get("namespace", "default"),
            creation_spec=d["creation_spec"],
            max_restarts=d["max_restarts"],
            state=d["state"],
            node_id=d["node_id"] and NodeID(d["node_id"]),
            worker_id=d["worker_id"] and WorkerID(d["worker_id"]),
            address=d["address"] and tuple(d["address"]),
            fast_port=d.get("fast_port"),
            num_restarts=d["num_restarts"],
            death_cause=d["death_cause"],
            handled_deaths={WorkerID(w) for w in d["handled_deaths"]},
        )

    def public_view(self) -> dict:
        return {
            "actor_id": self.actor_id.hex(),
            "job_id": self.job_id.hex(),
            "name": self.name,
            "state": self.state,
            "address": self.address,
            "fast_port": self.fast_port,
            "node_id": self.node_id.hex() if self.node_id else None,
            "num_restarts": self.num_restarts,
            "max_restarts": self.max_restarts,
            "death_cause": self.death_cause,
        }


@dataclass
class PgRecord:
    pg_id: PlacementGroupID
    name: Optional[str]
    bundles: List[ResourceRequest]
    strategy: str
    state: str = PG_PENDING
    bundle_nodes: List[Optional[NodeID]] = field(default_factory=list)
    creator_job: Optional[JobID] = None

    def public_view(self) -> dict:
        return {
            "pg_id": self.pg_id.hex(),
            "name": self.name,
            "strategy": self.strategy,
            "state": self.state,
            "bundles": [b.to_dict() for b in self.bundles],
            "bundle_nodes": [n.hex() if n else None for n in self.bundle_nodes],
        }

    def to_store(self) -> dict:
        return {
            "pg_id": self.pg_id.binary(),
            "name": self.name,
            "bundles": [b.to_dict() for b in self.bundles],
            "strategy": self.strategy,
            "state": self.state,
            "bundle_nodes": [n and n.binary() for n in self.bundle_nodes],
            "creator_job": self.creator_job and self.creator_job.binary(),
        }

    @classmethod
    def from_store(cls, d: dict) -> "PgRecord":
        return cls(
            pg_id=PlacementGroupID(d["pg_id"]),
            name=d["name"],
            bundles=[ResourceRequest.from_dict(b) for b in d["bundles"]],
            strategy=d["strategy"],
            state=d["state"],
            bundle_nodes=[n and NodeID(n) for n in d["bundle_nodes"]],
            creator_job=d["creator_job"] and JobID(d["creator_job"]),
        )


@dataclass
class JobRecord:
    job_id: JobID
    driver_address: Optional[Tuple[str, int]]
    start_time: float
    state: str = "RUNNING"
    entrypoint: str = ""


class RayletHandle:
    """GCS-side client to one raylet."""

    def __init__(self, address: Tuple[str, int]):
        self.address = address
        self.client = RetryableRpcClient(address, deadline_s=10.0)

    def close(self):
        self.client.close()


class GcsServer:
    """All managers in one process, handlers on one event loop."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_dir: Optional[str] = None,
                 leader_epoch: Optional[int] = None):
        from .kv import InternalKV
        from .storage import GcsTableStorage

        # Leadership fencing (gcs/failover.py): every leader incarnation
        # carries a monotonically-increasing epoch, persisted next to the
        # table log.  A promoted standby mints epoch+1; a primary that
        # learns of a higher epoch (step_down RPC, or a raylet report
        # stamped with one) deposes itself instead of split-braining.
        # Reference contract: GCS restart + NotifyGCSRestart
        # (src/ray/protobuf/node_manager.proto RequestResourceReport path).
        self._epoch_path = (os.path.join(persist_dir, "leader_epoch")
                            if persist_dir else None)
        if leader_epoch is not None:
            self.leader_epoch = int(leader_epoch)
        else:
            self.leader_epoch = 1
            if self._epoch_path and os.path.exists(self._epoch_path):
                try:
                    with open(self._epoch_path) as f:
                        self.leader_epoch = int(f.read().strip() or 1)
                except (OSError, ValueError):
                    pass
        if self._epoch_path:
            try:
                os.makedirs(persist_dir, exist_ok=True)
                with open(self._epoch_path, "w") as f:
                    f.write(str(self.leader_epoch))
            except OSError:
                logger.exception("could not persist leader epoch")
        self.deposed = False
        self._deposed_by: Optional[int] = None
        # Deposition survives restarts: a supervisor-restarted old leader
        # must come back FENCED, not as a fresh epoch-N claimant (operator
        # remediation = remove the marker file after reconciling).
        self._deposed_path = (os.path.join(persist_dir, "deposed_by")
                              if persist_dir else None)
        if (leader_epoch is not None and self._deposed_path
                and os.path.exists(self._deposed_path)):
            try:  # explicit promotion supersedes any stale marker
                os.unlink(self._deposed_path)
            except OSError:
                pass
        if (leader_epoch is None and self._deposed_path
                and os.path.exists(self._deposed_path)):
            try:
                with open(self._deposed_path) as f:
                    self._deposed_by = int(f.read().strip())
                self.deposed = True
                logger.warning(
                    "GCS booting DEPOSED (epoch %d superseded by %d); "
                    "remove %s to force-reclaim leadership",
                    self.leader_epoch, self._deposed_by, self._deposed_path)
            except (OSError, ValueError):
                pass

        self.server = RpcServer(host, port)
        self.publisher = Publisher()
        self.publisher.attach(self.server)
        self.view = ClusterView()
        self.storage: Optional[GcsTableStorage] = (
            GcsTableStorage(f"{persist_dir}/gcs_tables.log") if persist_dir else None)
        self.kv = InternalKV(self.storage)
        self._raylets: Dict[NodeID, RayletHandle] = {}
        self._actors: Dict[ActorID, ActorRecord] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}  # (namespace,name)
        self._pgs: Dict[PlacementGroupID, PgRecord] = {}
        self._jobs: Dict[JobID, JobRecord] = {}
        self._job_counter = 0
        self._task_events: List[dict] = []  # ring buffer
        self._stopped = False
        self._pending_actor_queue: List[ActorID] = []
        self._pending_pg_queue: List[PlacementGroupID] = []
        self._node_demands: Dict[NodeID, List[dict]] = {}  # autoscaler feed
        self._node_stats: Dict[NodeID, dict] = {}  # per-node system stats
        # object location directory (reference: GcsObjectManager / the
        # owner-reported object directory): oid -> node_id_hex ->
        # {"address": transfer endpoint, "spilled": bool, "size": int}.
        # Owners report coalesced batches (object_locations_update), cold
        # fetches resolve here, node death drops the node's column.
        self._object_locations: Dict[bytes, Dict[str, dict]] = {}
        # export API (util/export_events.py): attached post-boot by the
        # session owner when enable_export_api is set
        self._export_logger = None
        # Actors persisted ALIVE whose hosting raylet hasn't re-registered yet
        # after a GCS restart (reference: gcs_actor_manager.cc restart path —
        # wait for raylet reports, then fail over the unclaimed).
        self._unconfirmed_actors: set = set()
        self._recovered = False
        self._io = IoContext.current()
        if self.storage is not None:
            self._replay_tables()
        self._register_handlers()

    # ------------------------------------------------------------ persistence
    def _replay_tables(self):
        """Rebuild control-plane state from the table log (GCS restart).

        Nodes are NOT replayed — raylets outlive the GCS and re-register
        themselves (their report_resources gets ``unknown`` and triggers a
        fresh register_node carrying live actors + held bundles), which is
        how the reference handles GCS failover (NotifyGCSRestart,
        node_manager.proto:397).
        """
        for raw in self.storage.all("jobs").values():
            rec = JobRecord(JobID(raw["job_id"]),
                            raw["driver_address"] and tuple(raw["driver_address"]),
                            raw["start_time"], raw["state"], raw["entrypoint"])
            self._jobs[rec.job_id] = rec
        meta = self.storage.get("meta", b"job_counter")
        if meta:
            self._job_counter = meta["value"]
        for raw in self.storage.all("actors").values():
            rec = ActorRecord.from_store(raw)
            self._actors[rec.actor_id] = rec
            if rec.name is not None and rec.state != ACTOR_DEAD:
                self._named_actors[(rec.namespace, rec.name)] = rec.actor_id
            if rec.state == ACTOR_ALIVE:
                self._unconfirmed_actors.add(rec.actor_id)
            elif rec.state in (ACTOR_PENDING, ACTOR_RESTARTING):
                self._pending_actor_queue.append(rec.actor_id)
        for raw in self.storage.all("pgs").values():
            rec = PgRecord.from_store(raw)
            self._pgs[rec.pg_id] = rec
            if rec.state == PG_CREATED:
                # bundle placements must be re-claimed by re-registering
                # raylets; unclaimed ones are rescheduled by the reconciler
                rec.bundle_nodes = [None] * len(rec.bundles)
            elif rec.state in (PG_PENDING, PG_RESCHEDULING):
                self._pending_pg_queue.append(rec.pg_id)
        if self._actors or self._pgs or self._jobs:
            self._recovered = True

    def _persist_actor(self, rec: ActorRecord):
        if self.storage is not None:
            self.storage.put("actors", rec.actor_id.binary(), rec.to_store())

    def _persist_pg(self, rec: PgRecord):
        if self.storage is not None:
            self.storage.put("pgs", rec.pg_id.binary(), rec.to_store())

    def _persist_job(self, rec: JobRecord):
        if self.storage is not None:
            self.storage.put("jobs", rec.job_id.binary(), {
                "job_id": rec.job_id.binary(),
                "driver_address": rec.driver_address,
                "start_time": rec.start_time,
                "state": rec.state,
                "entrypoint": rec.entrypoint,
            })

    # ------------------------------------------------------------------ setup
    def _register_handlers(self):
        s = self.server
        for name in (
            "register_node", "unregister_node", "report_resources", "get_all_nodes",
            "get_cluster_load", "update_system_config",
            "get_cluster_resources", "check_alive",
            "register_job", "finish_job", "get_all_jobs", "get_next_job_id",
            "register_actor", "register_actors", "report_actor_state",
            "get_actor", "get_actor_by_name",
            "list_actors", "kill_actor",
            "create_placement_group", "remove_placement_group", "get_placement_group",
            "wait_placement_group_ready", "list_placement_groups",
            "kv_put", "kv_get", "kv_del", "kv_keys", "kv_exists",
            "add_task_events", "get_task_events",
            "get_system_config", "health_check", "debug_state",
            "publish_worker_log", "fetch_table_log",
            "get_leader_info", "step_down",
            "object_locations_update", "get_object_locations",
        ):
            s.register(name, self._fenced(name, getattr(self, f"h_{name}")))

    # methods still answered after deposition: discovery/fencing plus the
    # log tail (harmless reads a late standby may still be draining)
    _DEPOSED_OK = frozenset({"get_leader_info", "step_down", "health_check",
                             "fetch_table_log", "standby_info"})

    def _fenced(self, name: str, handler):
        if name in self._DEPOSED_OK:
            return handler

        async def guarded(**kwargs):
            if self.deposed:
                from ray_tpu.common.status import GcsDeposedError

                raise GcsDeposedError(self.leader_epoch,
                                      self._deposed_by or 0)
            return await handler(**kwargs)

        return guarded

    def attach_export_logger(self, session_dir: str) -> None:
        """Start writing structured export events (actor/node/job/PG
        state transitions) under ``session_dir`` when the
        ``enable_export_api`` flag is set (reference: export API,
        src/ray/util/event.cc)."""
        if GLOBAL_CONFIG.get("enable_export_api"):
            from ray_tpu.util.export_events import ExportEventLogger

            self._export_logger = ExportEventLogger(session_dir)

    def _export(self, source_type: str, **event_data) -> None:
        if self._export_logger is not None:
            self._export_logger.emit(source_type, event_data)

    def _publish_actor(self, rec: ActorRecord) -> None:
        """Chokepoint for actor state changes: pubsub + export event."""
        self.publisher.publish("actor", rec.actor_id.hex(),
                               rec.public_view())
        self._export("EXPORT_ACTOR", **rec.public_view())

    def _publish_pg(self, rec: PgRecord) -> None:
        self.publisher.publish("pg", rec.pg_id.hex(), rec.public_view())
        self._export("EXPORT_PLACEMENT_GROUP", **rec.public_view())

    def start(self):
        self.server.start()
        self._io.spawn_threadsafe(self._health_loop())
        self._io.spawn_threadsafe(self._driver_health_loop())
        if self._recovered:
            self._io.spawn_threadsafe(self._reconcile_after_restart())

    def stop(self):
        self._stopped = True
        if self._export_logger is not None:
            self._export_logger.close()
        for h in self._raylets.values():
            h.close()
        self.server.stop()
        self.kv.close()
        if self.storage is not None:
            self.storage.close()

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    # ------------------------------------------------------------- node mgmt
    async def h_register_node(self, node_id: bytes, address, resources: dict, labels: dict,
                              object_store_address: Optional[str] = None,
                              transfer_address=None,
                              live_actors: Optional[List[dict]] = None,
                              held_bundles: Optional[List[dict]] = None):
        nid = NodeID(node_id)
        entry = NodeEntry(
            node_id=nid,
            address=tuple(address),
            resources=NodeResources(resources, labels),
            object_store_address=object_store_address,
            transfer_address=tuple(transfer_address) if transfer_address
            else None,
        )
        self.view.upsert(entry)
        self._raylets[nid] = RayletHandle(tuple(address))
        self.publisher.publish("node", nid.hex(), {"state": "ALIVE", "address": tuple(address)})
        self._export("EXPORT_NODE", node_id=nid.hex(), state="ALIVE",
                     address=list(address), resources=dict(resources))
        logger.info("node %s registered at %s", nid.hex()[:8], address)
        # Re-registration after a GCS restart: the raylet reports what it
        # still hosts so replayed records can be re-confirmed instead of
        # restarted (reference: raylet re-report on NotifyGCSRestart).
        stale_workers: List[bytes] = []
        for info in live_actors or []:
            rec = self._actors.get(ActorID(info["actor_id"]))
            # Only actors still awaiting reconfirmation may be reclaimed: a
            # raylet that re-registers AFTER the reconcile window must not
            # re-point a record the reconciler already failed over (that
            # incarnation may be restarting elsewhere — reclaiming it would
            # leave two live copies with clients routed to the stale one).
            # The stale copy can't just be skipped either: left alone it
            # would run forever holding its lease, so it is killed here.
            if rec is None or rec.actor_id not in self._unconfirmed_actors:
                stale_workers.append(info["worker_id"])
                continue
            rec.state = ACTOR_ALIVE
            rec.node_id = nid
            rec.worker_id = WorkerID(info["worker_id"])
            rec.address = info["address"] and tuple(info["address"])
            self._unconfirmed_actors.discard(rec.actor_id)
            self._persist_actor(rec)
            self._publish_actor(rec)
        stale_pgs = []
        for info in held_bundles or []:
            rec = self._pgs.get(PlacementGroupID(info["pg_id"]))
            if rec is None or rec.state != PG_CREATED:
                # the PG was removed, or is being rescheduled from scratch:
                # the raylet's surviving allocations must be freed, not kept
                stale_pgs.append(info["pg_id"])
                continue
            for idx in info["indices"]:
                # only fill unclaimed slots — never steal a slot the
                # reconciler already rescheduled onto another node
                if 0 <= idx < len(rec.bundle_nodes) and rec.bundle_nodes[idx] is None:
                    rec.bundle_nodes[idx] = nid
            self._persist_pg(rec)
        if stale_pgs or stale_workers:
            handle = self._raylets.get(nid)

            async def drop_stale(handle=handle, pgs=stale_pgs,
                                 workers=stale_workers):
                for pg_raw in pgs:
                    try:
                        await handle.client.call_async(
                            "return_bundles", pg_id=pg_raw, timeout=10.0)
                    except Exception:  # noqa: BLE001
                        pass
                for wid_raw in workers:
                    try:
                        await handle.client.call_async(
                            "kill_worker", worker_id=wid_raw, timeout=10.0)
                    except Exception:  # noqa: BLE001
                        pass

            if handle is not None:
                self._io.spawn_threadsafe(drop_stale())
        self._kick_pending()
        return {"ok": True, "system_config": GLOBAL_CONFIG.system_config_json()}

    async def _reconcile_after_restart(self):
        """After a restart, give surviving raylets one reconnect window, then
        fail over whatever nobody re-claimed: ALIVE actors on missing nodes
        restart through the normal budgeted path; CREATED PGs with unclaimed
        bundles go back through 2PC scheduling."""
        await asyncio.sleep(GLOBAL_CONFIG.get("gcs_restart_reconcile_delay_s"))
        if self._stopped:
            return
        for aid in list(self._unconfirmed_actors):
            # Re-check membership at each step: _schedule_actor below awaits
            # RPCs, and a raylet's h_register_node may reclaim a later entry
            # of this snapshot meanwhile — failing that one over too would
            # fork the actor into two live copies.
            if aid not in self._unconfirmed_actors:
                continue
            self._unconfirmed_actors.discard(aid)
            rec = self._actors.get(aid)
            if rec is not None and rec.state == ACTOR_ALIVE:
                await self._on_actor_failure(
                    rec, "hosting node lost across GCS restart")
        for rec in list(self._pgs.values()):
            if rec.state == PG_CREATED and any(n is None for n in rec.bundle_nodes):
                # tear down surviving partial placements, then reschedule
                for nid in {n for n in rec.bundle_nodes if n is not None}:
                    handle = self._raylets.get(nid)
                    if handle:
                        try:
                            await handle.client.call_async(
                                "return_bundles", pg_id=rec.pg_id.binary(),
                                timeout=10.0)
                        except Exception:  # noqa: BLE001
                            pass
                rec.state = PG_RESCHEDULING
                rec.bundle_nodes = [None] * len(rec.bundles)
                self._persist_pg(rec)
                self._pending_pg_queue.append(rec.pg_id)
        self._kick_pending()

    async def h_unregister_node(self, node_id: bytes):
        nid = NodeID(node_id)
        await self._on_node_dead(nid, "unregistered")
        return True

    async def h_report_resources(self, node_id: bytes, snapshot: dict, seq: int,
                                 pending: Optional[List[dict]] = None,
                                 stats: Optional[dict] = None,
                                 leader_epoch: Optional[int] = None):
        if leader_epoch is not None and int(leader_epoch) > self.leader_epoch:
            # the raylet has already followed a newer leader: fence ourselves
            # even if the promoted standby's step_down never reached us
            await self.h_step_down(epoch=int(leader_epoch))
            from ray_tpu.common.status import GcsDeposedError

            raise GcsDeposedError(self.leader_epoch, int(leader_epoch))
        nid = NodeID(node_id)
        entry = self.view.get(nid)
        if entry is None:
            return {"ok": False, "unknown": True}  # raylet should re-register
        self._node_demands[nid] = list(pending or [])
        if stats is not None:
            # per-node system stats (mem/load/workers) for the dashboard's
            # node view + per-node Prometheus gauges (reference: per-node
            # metrics agents, dashboard/modules/reporter)
            self._node_stats[nid] = stats
        self.view.update_resources(nid, snapshot, seq)
        self.publisher.publish("resources", nid.hex(), {"snapshot": snapshot, "seq": seq})
        self._kick_pending()
        return {"ok": True}

    async def h_update_system_config(self, key: str, value):
        """Set one cluster-wide flag and push it to every raylet (the
        autoscaler flips autoscaling_enabled this way)."""
        from ray_tpu.common.config import GLOBAL_CONFIG

        GLOBAL_CONFIG.set_system_config_value(key, value)
        self.publisher.publish("system_config", key, {"value": value})
        return True

    async def h_get_cluster_load(self):
        """Aggregate pending demand for the autoscaler (reference:
        GcsAutoscalerStateManager cluster resource state)."""
        lease_demands: List[dict] = []
        for nid, demands in self._node_demands.items():
            entry = self.view.get(nid)
            if entry is not None and entry.alive:
                lease_demands.extend(demands)
        pg_demands: List[List[dict]] = []
        for pg_id in self._pending_pg_queue:
            rec = self._pgs.get(pg_id)
            if rec is not None:
                pg_demands.append([b.to_dict() for b in rec.bundles])
        return {"lease_demands": lease_demands, "pg_demands": pg_demands}

    async def h_get_all_nodes(self):
        return [
            {
                "node_id": e.node_id.binary(),
                "address": e.address,
                "alive": e.alive,
                "resources": e.resources.snapshot(),
                "object_store_address": e.object_store_address,
                "transfer_address": e.transfer_address,
                "stats": self._node_stats.get(e.node_id, {}),
            }
            for e in self.view.all_nodes()
        ]

    async def h_get_cluster_resources(self):
        return {
            "total": self.view.total_resources(),
            "available": self.view.available_resources(),
        }

    async def h_check_alive(self, node_ids: List[bytes]):
        out = []
        for raw in node_ids:
            e = self.view.get(NodeID(raw))
            out.append(bool(e and e.alive))
        return out

    # -------------------------------------------------- object locations
    async def h_object_locations_update(self, updates: List[dict]):
        """Owner-coalesced location churn (one RPC per flush window, not
        per object — the PR-7 coalesced-pubsub discipline).  Each update:
        ``{"op": "add"|"remove"|"spill", "object_id", "node_id",
        "address"?, "size"?}``; node_id/address describe the COPY, not
        the owner."""
        events = []
        for u in updates:
            oid = u["object_id"]
            op = u.get("op", "add")
            if op == "remove" and "node_id" not in u:
                # owner freed the object: every copy's entry dies with it
                if self._object_locations.pop(oid, None) is not None:
                    events.append({"op": "remove", "object_id": oid})
                continue
            nid_hex = u["node_id"].hex() if isinstance(u["node_id"], bytes) \
                else str(u["node_id"])
            locs = self._object_locations.setdefault(oid, {})
            if op == "remove":
                locs.pop(nid_hex, None)
                if not locs:
                    self._object_locations.pop(oid, None)
            else:
                loc = locs.setdefault(nid_hex, {})
                if u.get("address") is not None:
                    loc["address"] = tuple(u["address"])
                if u.get("size") is not None:
                    loc["size"] = int(u["size"])
                loc["spilled"] = bool(op == "spill" or loc.get("spilled"))
                if op == "add":
                    loc["spilled"] = False  # re-sealed after a demotion
            events.append({"op": op, "object_id": oid, "node_id": nid_hex})
        # one batched publication per flush (Publisher coalesces wakeups)
        for ev in events:
            self.publisher.publish("object_loc", ev["object_id"].hex()
                                   if isinstance(ev["object_id"], bytes)
                                   else str(ev["object_id"]), ev)
        return {"ok": True, "applied": len(events)}

    async def h_get_object_locations(self, object_ids: List[bytes]):
        """Bulk cold-path resolution: oid-hex -> [{node_id, address,
        spilled, size}] for every known copy, live nodes only."""
        out = {}
        for oid in object_ids:
            locs = self._object_locations.get(oid)
            if not locs:
                continue
            rows = []
            for nid_hex, loc in locs.items():
                e = self.view.get(NodeID.from_hex(nid_hex))
                if e is None or not e.alive:
                    continue
                rows.append({"node_id": nid_hex,
                             "address": loc.get("address")
                             or (e.transfer_address and
                                 tuple(e.transfer_address)),
                             "spilled": bool(loc.get("spilled")),
                             "size": loc.get("size")})
            if rows:
                out[oid.hex()] = rows
        return out

    def _drop_node_locations(self, nid: NodeID) -> None:
        nid_hex = nid.hex()
        for oid in list(self._object_locations):
            locs = self._object_locations[oid]
            if locs.pop(nid_hex, None) is not None and not locs:
                del self._object_locations[oid]

    async def _health_loop(self):
        period = GLOBAL_CONFIG.get("health_check_period_ms") / 1000.0
        threshold = GLOBAL_CONFIG.get("health_check_failure_threshold")
        await asyncio.sleep(GLOBAL_CONFIG.get("health_check_initial_delay_ms") / 1000.0)
        misses: Dict[NodeID, int] = {}
        while not self._stopped:
            if self.deposed:
                # fenced: a deposed leader must stop COMMANDING the
                # cluster too (declaring nodes dead, rescheduling actors)
                await asyncio.sleep(period)
                continue
            for entry in list(self.view.alive_nodes()):
                handle = self._raylets.get(entry.node_id)
                if handle is None:
                    continue
                try:
                    await handle.client._client.call_async(
                        "health_check", timeout=GLOBAL_CONFIG.get("health_check_timeout_ms") / 1000.0
                    )
                    misses[entry.node_id] = 0
                except Exception:  # noqa: BLE001
                    misses[entry.node_id] = misses.get(entry.node_id, 0) + 1
                    if misses[entry.node_id] >= threshold:
                        await self._on_node_dead(entry.node_id, "health check failed")
            await asyncio.sleep(period)

    async def _on_node_dead(self, nid: NodeID, reason: str):
        entry = self.view.mark_dead(nid)
        if entry is None:
            return
        logger.warning("node %s dead: %s", nid.hex()[:8], reason)
        handle = self._raylets.pop(nid, None)
        if handle:
            handle.close()
        self.publisher.publish("node", nid.hex(), {"state": "DEAD", "reason": reason})
        self._export("EXPORT_NODE", node_id=nid.hex(), state="DEAD",
                     reason=reason)
        # its object copies died with it: pullers must not be routed there
        self._drop_node_locations(nid)
        # fail over actors that lived there
        for rec in list(self._actors.values()):
            if rec.node_id == nid and rec.state in (ACTOR_ALIVE, ACTOR_PENDING, ACTOR_RESTARTING):
                await self._on_actor_failure(rec, f"node died: {reason}")
        # reschedule PG bundles that lived there
        for pg in list(self._pgs.values()):
            if pg.state in (PG_CREATED, PG_PENDING) and any(b == nid for b in pg.bundle_nodes):
                pg.state = PG_RESCHEDULING
                pg.bundle_nodes = [None if b == nid else b for b in pg.bundle_nodes]
                self._persist_pg(pg)
                self._publish_pg(pg)
                self._pending_pg_queue.append(pg.pg_id)
        self._kick_pending()

    # ------------------------------------------------------------------ jobs
    async def h_get_next_job_id(self):
        self._job_counter += 1
        if self.storage is not None:
            self.storage.put("meta", b"job_counter", {"value": self._job_counter})
        return JobID.from_int(self._job_counter).binary()

    async def h_register_job(self, job_id: bytes, driver_address=None, entrypoint: str = ""):
        jid = JobID(job_id)
        self._jobs[jid] = JobRecord(jid, driver_address and tuple(driver_address), time.time(), entrypoint=entrypoint)
        self._persist_job(self._jobs[jid])
        self.publisher.publish("job", jid.hex(), {"state": "RUNNING"})
        self._export("EXPORT_JOB", job_id=jid.hex(), state="RUNNING",
                     entrypoint=entrypoint)
        return True

    async def h_finish_job(self, job_id: bytes):
        jid = JobID(job_id)
        rec = self._jobs.get(jid)
        if rec:
            rec.state = "FINISHED"
            self._persist_job(rec)
            self.publisher.publish("job", jid.hex(), {"state": "FINISHED"})
            self._export("EXPORT_JOB", job_id=jid.hex(), state="FINISHED")
        # tear down the job's detached=False actors
        for actor in list(self._actors.values()):
            if actor.job_id == jid and actor.state not in (ACTOR_DEAD,):
                await self._kill_actor_internal(actor, "job finished")
        return True

    async def _driver_health_loop(self):
        """Finish jobs whose driver died without calling finish_job (SIGKILL,
        SIGTERM mid-sleep, crashed client session driver): otherwise the
        job's actors hold their resources forever and starve the cluster.
        Reference: the GCS job manager observes driver disconnects
        (gcs/gcs_server/gcs_job_manager.cc) and runs the same teardown as a
        graceful exit."""
        period = GLOBAL_CONFIG.get("health_check_period_ms") / 1000.0
        threshold = GLOBAL_CONFIG.get("health_check_failure_threshold")
        timeout = GLOBAL_CONFIG.get("health_check_timeout_ms") / 1000.0
        misses: Dict[JobID, int] = {}
        clients: Dict[JobID, RpcClient] = {}
        while not self._stopped:
            await asyncio.sleep(period)
            if self.deposed:
                continue  # fenced: no job teardown from a zombie leader
            for jid, rec in list(self._jobs.items()):
                if rec.state != "RUNNING" or not rec.driver_address:
                    c = clients.pop(jid, None)
                    if c is not None:
                        c.close()
                    misses.pop(jid, None)
                    continue
                client = clients.get(jid)
                if client is None:
                    # plain RpcClient: each miss must count toward the
                    # threshold, so no retry layer (it reconnects per call)
                    client = RpcClient(tuple(rec.driver_address))
                    clients[jid] = client
                try:
                    await client.call_async("ping", timeout=timeout)
                    misses[jid] = 0
                    continue
                except Exception:  # noqa: BLE001 — count toward threshold
                    misses[jid] = misses.get(jid, 0) + 1
                    if misses[jid] < threshold:
                        continue
                logger.warning(
                    "driver of job %s unreachable x%d; finishing job",
                    jid.hex()[:8], misses[jid])
                try:
                    await self.h_finish_job(jid.binary())
                except Exception:  # noqa: BLE001 — teardown failure must
                    # not kill this loop; the job stays RUNNING and the
                    # finish is retried at the next threshold crossing
                    logger.exception("finishing job %s failed", jid.hex()[:8])
                c = clients.pop(jid, None)
                if c is not None:
                    c.close()
                misses.pop(jid, None)

    async def h_get_all_jobs(self):
        return [
            {"job_id": j.job_id.hex(), "state": j.state, "start_time": j.start_time,
             "entrypoint": j.entrypoint}
            for j in self._jobs.values()
        ]

    # ---------------------------------------------------------------- actors
    async def h_register_actor(self, creation_spec: bytes, actor_id: bytes, job_id: bytes,
                               name: Optional[str] = None, namespace: str = "default",
                               max_restarts: int = 0):
        aid = ActorID(actor_id)
        if name is not None:
            key = (namespace, name)
            if key in self._named_actors:
                existing = self._actors.get(self._named_actors[key])
                if existing is not None and existing.state != ACTOR_DEAD:
                    return {"ok": False, "error": f"actor name {name!r} taken"}
            self._named_actors[key] = aid
        rec = ActorRecord(
            actor_id=aid, job_id=JobID(job_id), name=name,
            namespace=namespace,
            creation_spec=creation_spec, max_restarts=max_restarts,
        )
        self._actors[aid] = rec
        self._persist_actor(rec)
        # Registration returns immediately (reference semantics: actor
        # creation is ASYNC — ActorClass.remote() must not block the driver
        # for the whole spawn chain); scheduling proceeds concurrently, so
        # a burst of creations parallelizes across the worker pool's
        # startup concurrency instead of serializing end-to-end.
        self._io.spawn(self._schedule_actor(rec))
        return {"ok": True}

    async def h_register_actors(self, specs: List[dict], job_id: bytes):
        """Coalesced unnamed-actor registration: one RPC registers a whole
        burst of creations (the driver batches per loop tick).  Named
        actors keep the per-actor RPC — their callers need the synchronous
        name-collision ack."""
        jid = JobID(job_id)
        errors: List[str] = []
        for e in specs:
            try:
                rec = ActorRecord(
                    actor_id=ActorID(e["actor_id"]), job_id=jid, name=None,
                    namespace=e.get("namespace", "default"),
                    creation_spec=e["creation_spec"],
                    max_restarts=e.get("max_restarts", 0),
                )
                self._actors[rec.actor_id] = rec
                self._persist_actor(rec)
                self._io.spawn(self._schedule_actor(rec))
            except Exception as ex:  # noqa: BLE001 — one bad spec must not
                # poison the rest of the batch
                errors.append(f"{e.get('actor_id', b'').hex()}: {ex!r}")
        return {"ok": not errors, "errors": errors}

    async def _schedule_actor(self, rec: ActorRecord):
        """GcsActorScheduler equivalent: pick node, ask its raylet to start the
        actor (raylet owns worker pool + resource accounting)."""
        import pickle

        spec = pickle.loads(rec.creation_spec)
        node = policies.pick_node(self.view, spec.required_resources, spec.scheduling_strategy)
        if node is None:
            if rec.actor_id not in self._pending_actor_queue:
                self._pending_actor_queue.append(rec.actor_id)
            return
        handle = self._raylets.get(node.node_id)
        if handle is None:
            return
        rec.node_id = node.node_id
        self._persist_actor(rec)
        try:
            reply = await handle.client.call_async(
                "start_actor", creation_spec=rec.creation_spec, timeout=60.0
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("start_actor on %s failed: %s", node.node_id.hex()[:8], e)
            self._pending_actor_queue.append(rec.actor_id)
            return
        if not reply.get("ok"):
            if reply.get("fatal"):
                # e.g. runtime-env setup failure: retrying placement can
                # never succeed — fail the actor with the cause
                rec.max_restarts = rec.num_restarts
                await self._on_actor_failure(rec, reply.get("reason", "fatal"))
                return
            if rec.actor_id not in self._pending_actor_queue:
                self._pending_actor_queue.append(rec.actor_id)

    async def h_report_actor_state(self, actor_id: bytes, state: str,
                                   worker_id: Optional[bytes] = None,
                                   address=None, node_id: Optional[bytes] = None,
                                   death_cause: str = "",
                                   fast_port: Optional[int] = None):
        rec = self._actors.get(ActorID(actor_id))
        if rec is None:
            return False
        if state == ACTOR_ALIVE:
            rec.state = ACTOR_ALIVE
            rec.worker_id = worker_id and WorkerID(worker_id)
            rec.address = address and tuple(address)
            rec.fast_port = fast_port
            if node_id:
                rec.node_id = NodeID(node_id)
            self._unconfirmed_actors.discard(rec.actor_id)
            self._persist_actor(rec)
        elif state == ACTOR_DEAD:
            # Idempotency: a death report is only valid once per worker
            # incarnation — RPC retries deliver duplicates, which must not
            # burn the restart budget twice.
            wid = worker_id and WorkerID(worker_id)
            if wid is not None and wid in rec.handled_deaths:
                return True
            if rec.state == ACTOR_ALIVE:
                if wid is not None and rec.worker_id is not None and wid != rec.worker_id:
                    return True  # stale report about an older incarnation
            elif rec.state != ACTOR_PENDING:  # RESTARTING/DEAD: stale
                return True
            if wid is not None:
                rec.handled_deaths.add(wid)
            await self._on_actor_failure(rec, death_cause or "worker died")
            return True
        self._publish_actor(rec)
        return True

    async def _on_actor_failure(self, rec: ActorRecord, cause: str):
        if rec.state == ACTOR_DEAD:
            return
        if rec.num_restarts < rec.max_restarts or rec.max_restarts < 0:
            rec.num_restarts += 1
            rec.state = ACTOR_RESTARTING
            rec.address = None
            rec.worker_id = None
            self._persist_actor(rec)
            self._publish_actor(rec)
            await self._schedule_actor(rec)
        else:
            rec.state = ACTOR_DEAD
            rec.death_cause = cause
            self._persist_actor(rec)
            self._publish_actor(rec)

    async def h_get_actor(self, actor_id: bytes):
        rec = self._actors.get(ActorID(actor_id))
        return rec and rec.public_view()

    async def h_get_actor_by_name(self, name: str, namespace: str = "default"):
        aid = self._named_actors.get((namespace, name))
        if aid is None:
            return None
        rec = self._actors.get(aid)
        return rec and rec.public_view()

    async def h_list_actors(self):
        return [r.public_view() for r in self._actors.values()]

    async def h_kill_actor(self, actor_id: bytes, no_restart: bool = True):
        rec = self._actors.get(ActorID(actor_id))
        if rec is None:
            return False
        await self._kill_actor_internal(rec, "killed via kill_actor", no_restart=no_restart)
        return True

    async def _kill_actor_internal(self, rec: ActorRecord, cause: str, no_restart: bool = True):
        if no_restart:
            rec.max_restarts = rec.num_restarts  # exhaust restart budget
        node = rec.node_id and self._raylets.get(rec.node_id)
        if node is not None and rec.worker_id is not None:
            try:
                await node.client.call_async(
                    "kill_worker", worker_id=rec.worker_id.binary(), timeout=5.0
                )
            except Exception:  # noqa: BLE001
                pass
        await self._on_actor_failure(rec, cause)

    # --------------------------------------------------------------- PGs/2PC
    async def h_create_placement_group(self, pg_id: bytes, bundles: List[dict], strategy: str,
                                       name: Optional[str] = None, job_id: Optional[bytes] = None):
        pgid = PlacementGroupID(pg_id)
        rec = PgRecord(
            pg_id=pgid, name=name,
            bundles=[ResourceRequest.from_dict(b) for b in bundles],
            strategy=strategy,
            bundle_nodes=[None] * len(bundles),
            creator_job=job_id and JobID(job_id),
        )
        self._pgs[pgid] = rec
        self._persist_pg(rec)
        await self._schedule_pg(rec)
        return {"ok": True, "state": rec.state}

    async def _schedule_pg(self, rec: PgRecord):
        placement = policies.place_bundles(self.view, rec.bundles, rec.strategy)
        if placement is None:
            if rec.pg_id not in self._pending_pg_queue:
                self._pending_pg_queue.append(rec.pg_id)
            return
        # 2PC (reference: gcs_placement_group_scheduler.h:122-124): prepare all,
        # then commit all; any prepare failure returns the prepared ones.
        by_node: Dict[NodeID, List[int]] = {}
        for idx, nid in enumerate(placement):
            by_node.setdefault(nid, []).append(idx)
        prepared: List[NodeID] = []
        ok = True
        for nid, idxs in by_node.items():
            handle = self._raylets.get(nid)
            if handle is None:
                ok = False
                break
            try:
                res = await handle.client.call_async(
                    "prepare_bundles",
                    pg_id=rec.pg_id.binary(),
                    bundles={i: rec.bundles[i].to_dict() for i in idxs},
                    timeout=30.0,
                )
                if not res:
                    ok = False
                    break
                prepared.append(nid)
            except Exception:  # noqa: BLE001
                ok = False
                break
        if not ok:
            for nid in prepared:
                handle = self._raylets.get(nid)
                if handle:
                    try:
                        await handle.client.call_async(
                            "return_bundles", pg_id=rec.pg_id.binary(), timeout=10.0
                        )
                    except Exception:  # noqa: BLE001
                        pass
            if rec.pg_id not in self._pending_pg_queue:
                self._pending_pg_queue.append(rec.pg_id)
            return
        commit_failed = False
        for nid in by_node:
            handle = self._raylets.get(nid)
            if handle is None:
                commit_failed = True
                continue
            try:
                await handle.client.call_async(
                    "commit_bundles", pg_id=rec.pg_id.binary(), timeout=30.0
                )
            except Exception:  # noqa: BLE001 - unreachable raylet
                commit_failed = True
        if commit_failed:
            # Partial commit must not report CREATED — leases against the
            # uncommitted bundle would queue forever.  Tear down and retry.
            for nid in by_node:
                handle = self._raylets.get(nid)
                if handle:
                    try:
                        await handle.client.call_async(
                            "return_bundles", pg_id=rec.pg_id.binary(), timeout=10.0)
                    except Exception:  # noqa: BLE001
                        pass
            rec.state = PG_RESCHEDULING
            self._persist_pg(rec)
            if rec.pg_id not in self._pending_pg_queue:
                self._pending_pg_queue.append(rec.pg_id)
            return
        rec.bundle_nodes = list(placement)
        rec.state = PG_CREATED
        self._persist_pg(rec)
        self._publish_pg(rec)

    async def h_remove_placement_group(self, pg_id: bytes):
        rec = self._pgs.get(PlacementGroupID(pg_id))
        if rec is None:
            return False
        for nid in set(n for n in rec.bundle_nodes if n is not None):
            handle = self._raylets.get(nid)
            if handle:
                try:
                    await handle.client.call_async(
                        "return_bundles", pg_id=rec.pg_id.binary(), timeout=10.0
                    )
                except Exception:  # noqa: BLE001
                    pass
        rec.state = PG_REMOVED
        self._persist_pg(rec)
        self._publish_pg(rec)
        return True

    async def h_get_placement_group(self, pg_id: bytes):
        rec = self._pgs.get(PlacementGroupID(pg_id))
        return rec and rec.public_view()

    async def h_wait_placement_group_ready(self, pg_id: bytes, timeout_s: float = 30.0):
        pgid = PlacementGroupID(pg_id)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            rec = self._pgs.get(pgid)
            if rec is None:
                return {"ok": False, "error": "no such placement group"}
            if rec.state == PG_CREATED:
                return {"ok": True}
            if rec.state == PG_REMOVED:
                return {"ok": False, "error": "placement group removed"}
            await asyncio.sleep(0.05)
        return {"ok": False, "error": "timeout"}

    async def h_list_placement_groups(self):
        return [r.public_view() for r in self._pgs.values()]

    # ------------------------------------------------------------------- KV
    async def h_kv_put(self, namespace: str, key, value: bytes, overwrite: bool = True):
        return self.kv.put(namespace, key, value, overwrite)

    async def h_kv_get(self, namespace: str, key):
        return self.kv.get(namespace, key)

    async def h_kv_del(self, namespace: str, key):
        return self.kv.delete(namespace, key)

    async def h_kv_keys(self, namespace: str, prefix=b""):
        return self.kv.keys(namespace, prefix)

    async def h_kv_exists(self, namespace: str, key):
        return self.kv.exists(namespace, key)

    async def h_debug_state(self):
        """Control-plane introspection (reference: GCS debug_state dump +
        instrumented_io_context event stats): table sizes plus per-RPC-
        handler loop time, the `ray stack`-style view of where the GCS
        event loop goes."""
        return {
            "num_nodes": sum(1 for _ in self.view.all_nodes()),
            "num_actors": len(self._actors),
            "num_placement_groups": len(self._pgs),
            "num_jobs": len(self._jobs),
            "io_stats": dict(self._io.stats),
        }

    # ----------------------------------------------------------- task events
    async def h_add_task_events(self, events: List[dict]):
        self._task_events.extend(events)
        if len(self._task_events) > 100_000:
            self._task_events = self._task_events[-50_000:]
        return True

    async def h_get_task_events(self, job_id: Optional[bytes] = None, limit: int = 10_000):
        evs = self._task_events
        if job_id is not None:
            jid = JobID(job_id).hex()
            evs = [e for e in evs if e.get("job_id") == jid]
        return evs[-limit:]

    # ---------------------------------------------------------- worker logs
    async def h_publish_worker_log(self, job_id: str, pid: int,
                                   worker_id: str, stream: str,
                                   lines: List[str], actor_name: str = ""):
        """Relay a batch of worker stdout/stderr lines to subscribed
        drivers (reference: log_monitor.py tail → GCS pubsub → driver)."""
        self.publisher.publish("worker_log", job_id or "", {
            "job_id": job_id, "pid": pid, "worker_id": worker_id,
            "stream": stream, "lines": lines, "actor_name": actor_name,
        })
        return True

    # ------------------------------------------------------------------ misc
    async def h_get_system_config(self):
        return GLOBAL_CONFIG.system_config_json()

    async def h_health_check(self):
        return not self.deposed

    async def h_get_leader_info(self):
        return {"epoch": self.leader_epoch, "deposed": self.deposed}

    async def h_step_down(self, epoch: int):
        """Fencing: a promoted standby (or anyone relaying its epoch)
        tells this leader a higher incarnation exists."""
        if int(epoch) > self.leader_epoch and not self.deposed:
            self.deposed = True
            self._deposed_by = int(epoch)
            if self._deposed_path:
                def _persist(path=self._deposed_path,
                             epoch=self._deposed_by):
                    with open(path, "w") as f:
                        f.write(str(epoch))

                try:
                    # off-loop: the in-memory fence above already rejects
                    # control-plane calls; the marker write is durability
                    # only and must not park the (still-draining) loop
                    await asyncio.to_thread(_persist)
                except OSError:
                    logger.exception("could not persist deposition")
            logger.warning(
                "GCS stepping down: epoch %d superseded by %d — this "
                "instance now rejects all control-plane calls",
                self.leader_epoch, epoch)
            return True
        return self.deposed

    async def h_fetch_table_log(self, offset: int = 0,
                                generation: Optional[int] = None,
                                max_bytes: int = 1 << 20):
        """Log-shipping endpoint for a warm standby (gcs/failover.py).
        Reference role: Redis replication under the reference's
        redis_store_client.h-backed GCS FT."""
        if self.storage is None:
            return {"unsupported": True, "epoch": self.leader_epoch}
        reply = self.storage.read_chunk(offset, generation, max_bytes)
        reply["epoch"] = self.leader_epoch  # standby mints epoch+1 on promotion
        return reply

    def _kick_pending(self):
        """Retry pending actors/PGs (resources may have freed up)."""
        if self.deposed:
            return  # fenced: no scheduling commands from a zombie leader
        if not self._pending_actor_queue and not self._pending_pg_queue:
            return

        async def kick():
            actors, self._pending_actor_queue = self._pending_actor_queue, []
            for aid in actors:
                rec = self._actors.get(aid)
                if rec is not None and rec.state in (ACTOR_PENDING, ACTOR_RESTARTING):
                    await self._schedule_actor(rec)
            pgs, self._pending_pg_queue = self._pending_pg_queue, []
            for pgid in pgs:
                rec = self._pgs.get(pgid)
                if rec is not None and rec.state in (PG_PENDING, PG_RESCHEDULING):
                    await self._schedule_pg(rec)

        self._io.spawn_threadsafe(kick())


def main():
    import argparse
    import signal
    import threading

    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--persist-dir", default=None)
    parser.add_argument("--session-dir", default=None,
                        help="attach the export-event logger here (only "
                        "active when enable_export_api is set)")
    parser.add_argument("--system-config", default=None,
                        help="JSON system_config dict (the multi-process "
                        "launcher forwards the driver's init(system_config) "
                        "here so cluster-wide flags apply in this process)")
    args = parser.parse_args()
    if args.system_config:
        GLOBAL_CONFIG.initialize(args.system_config)
        GLOBAL_CONFIG.reset_cache()
    gcs = GcsServer(args.host, args.port, args.persist_dir)
    if args.session_dir:
        gcs.attach_export_logger(args.session_dir)
    gcs.start()
    print(f"GCS_READY {gcs.address[0]}:{gcs.address[1]}", flush=True)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    done.wait()
    gcs.stop()


if __name__ == "__main__":
    main()
