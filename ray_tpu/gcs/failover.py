"""Warm-standby GCS failover.

Reference: the reference keeps GCS state in Redis
(``src/ray/gcs/store_client/redis_store_client.h``) so a restarted or
replacement GCS process recovers from the external store, and raylets
re-attach via ``NotifyGCSRestart``. This image has no Redis, so the same
availability contract is built from the pieces we do have:

- the primary's :class:`~ray_tpu.gcs.storage.GcsTableStorage` append log
  is SHIPPED to the standby over the ``fetch_table_log`` RPC (pull-based,
  generation-aware so compactions restart the stream);
- the standby probes the primary; after ``failure_threshold`` missed
  polls it PROMOTES: a full :class:`GcsServer` boots from the replicated
  log on the standby's pre-announced address and runs the normal
  restart-reconcile path (raylets re-register, actors re-claimed);
- clients/raylets/workers reach the new leader because
  :class:`~ray_tpu.gcs.client.GcsClient` rotates through
  ``RT_GCS_STANDBY_ADDRS`` (comma-separated ``host:port``) when the
  current address stays dead.

Replication is asynchronous (like Redis async replication): mutations in
the last unpolled window can be lost on failover. Everything the
restart-reconcile path cannot re-derive is re-registered by the raylets
themselves, exactly as after an in-place GCS restart.

Before promotion the standby answers only ``standby_info`` /
``health_check`` on its address; any real GCS method returns a loud
"unknown method" error, which a rotating client treats as "not the
leader yet" and moves on.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional, Tuple

from ray_tpu.rpc.rpc import RetryableRpcClient, RpcServer

logger = logging.getLogger(__name__)


class GcsStandby:
    """Tail the primary's table log; promote to a full GcsServer when the
    primary stops answering."""

    def __init__(self, primary_address: Tuple[str, int], replica_dir: str,
                 host: str = "127.0.0.1", port: int = 0,
                 poll_interval_s: float = 0.5,
                 failure_threshold: int = 4):
        self.primary_address = tuple(primary_address)
        self.replica_dir = replica_dir
        os.makedirs(replica_dir, exist_ok=True)
        self._log_path = os.path.join(replica_dir, "gcs_tables.log")
        self._poll_interval_s = poll_interval_s
        self._failure_threshold = failure_threshold
        self._offset = 0
        self._generation: Optional[int] = None
        self._primary_epoch = 1  # last leader epoch seen in the log stream
        self._ever_synced = False  # at least one successful poll
        self.leader_epoch: Optional[int] = None  # set at promotion
        self._failures = 0
        # compaction refill: while the primary's post-compaction log is
        # being refetched, new-generation bytes land in a SIDE file and
        # the last complete generation stays promotable at _log_path
        self._next_path = self._log_path + ".next"
        self._refilling = False
        # test hook: simulate a standby↔primary partition (polls fail while
        # the primary stays up and reachable by everyone else)
        self._testing_drop_polls = False
        # test hook: threading.Event the replication loop blocks on right
        # after observing a compaction restart marker — lets tests kill
        # the primary deterministically inside the refetch window
        self._testing_refill_gate = None
        self._stop = threading.Event()
        self.promoted = threading.Event()
        self.server = None  # the promoted GcsServer
        # placeholder server pins the standby's address pre-promotion
        self._placeholder = RpcServer(host, port, validate_schemas=False)

        async def standby_info():
            return {"standby": True, "primary": self.primary_address,
                    "replicated_bytes": self._offset}

        async def health_check():
            return True

        self._placeholder.register("standby_info", standby_info)
        self._placeholder.register("health_check", health_check)
        self._placeholder.start()
        self.address = self._placeholder.address
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="gcs-standby")

    def start(self) -> "GcsStandby":
        self._thread.start()
        return self

    # ------------------------------------------------------------ replication
    def _run(self):
        # fresh replica: drop any stale logs from a previous incarnation
        for path in (self._log_path, self._next_path):
            if os.path.exists(path):
                os.unlink(path)
        log = open(self._log_path, "ab")
        client = RetryableRpcClient(self.primary_address, deadline_s=2.0)
        try:
            while not self._stop.is_set():
                try:
                    if self._testing_drop_polls:
                        raise ConnectionError("testing: partition injected")
                    chunk = client.call("fetch_table_log", timeout=5.0,
                                        offset=self._offset,
                                        generation=self._generation)
                    self._failures = 0
                    self._ever_synced = True
                    self._primary_epoch = int(chunk.get("epoch", 1))
                    if chunk.get("unsupported"):
                        logger.warning(
                            "primary GCS has no persistence; standby can "
                            "only fail over to an empty control plane")
                    elif chunk.get("restart"):
                        # Primary compacted: restart the stream — into a
                        # SIDE file. Truncating the replica in place would
                        # open a window (compaction observed → first new
                        # chunk landed) where a primary death promotes an
                        # EMPTY control plane, losing acknowledged writes.
                        # The last complete generation stays promotable at
                        # _log_path until the new one has fully landed.
                        log.close()
                        log = open(self._next_path, "wb")
                        self._refilling = True
                        self._offset = 0
                        self._generation = chunk["generation"]
                        gate = self._testing_refill_gate
                        if gate is not None:
                            while not gate.is_set() \
                                    and not self._stop.is_set():
                                gate.wait(0.05)
                        continue  # refetch immediately from 0
                    else:
                        self._generation = chunk["generation"]
                        data = chunk.get("data") or b""
                        if data:
                            log.write(data)
                            log.flush()
                            self._offset += len(data)
                        if self._refilling and len(data) < (1 << 20) \
                                and self._offset > 0:
                            # caught up with the live end of the new
                            # generation: atomically swap it in. The
                            # offset>0 guard keeps a transient empty
                            # chunk (primary-side read hiccup) from
                            # swapping in an EMPTY replica — the exact
                            # hole this path exists to close. (A
                            # genuinely empty compacted log stays
                            # unswapped: promoting the retained
                            # generation may resurrect recently deleted
                            # keys, which async replication tolerates;
                            # promoting emptiness loses everything.)
                            log.close()
                            os.replace(self._next_path, self._log_path)
                            log = open(self._log_path, "ab")
                            self._refilling = False
                        if len(data) == (1 << 20):
                            continue  # more buffered: drain fast
                except Exception:  # noqa: BLE001 — probe failure
                    self._failures += 1
                    logger.info("standby: primary probe failed (%d/%d)",
                                self._failures, self._failure_threshold)
                    if self._failures >= self._failure_threshold:
                        if not self._ever_synced:
                            # Never reached the primary at all: we hold no
                            # state and no epoch — promoting would serve an
                            # empty control plane and could mint an epoch
                            # BELOW the real leader's, inverting the fence.
                            # Keep trying instead.
                            logger.warning(
                                "standby: primary unreachable since boot; "
                                "refusing to promote without ever syncing")
                            self._failures = 0
                            self._stop.wait(self._poll_interval_s)
                            continue
                        log.close()
                        if self._refilling:
                            # Refuse to promote the half-refilled next
                            # generation (a partial compacted log is a
                            # SUBSET of committed keys); fall back to the
                            # retained last-complete generation.
                            logger.warning(
                                "standby: primary died mid-compaction "
                                "refill; promoting from the retained "
                                "previous generation")
                            try:
                                os.unlink(self._next_path)
                            except OSError:
                                pass
                            self._refilling = False
                        self._promote()
                        return
                self._stop.wait(self._poll_interval_s)
        finally:
            client.close()
            if not log.closed:
                log.close()

    # -------------------------------------------------------------- promotion
    def _promote(self):
        from ray_tpu.gcs.server import GcsServer

        host, port = self.address
        self.leader_epoch = self._primary_epoch + 1
        try:
            # actual promoted-log size: after a mid-refill fallback,
            # self._offset counts the DISCARDED partial next generation
            log_bytes = os.path.getsize(self._log_path)
        except OSError:
            log_bytes = 0
        logger.warning("standby promoting to GCS leader on %s:%d epoch %d "
                       "(replica log: %d bytes)", host, port,
                       self.leader_epoch, log_bytes)
        # free the pinned port, then boot the real control plane on it
        self._placeholder.stop()
        deadline = time.monotonic() + 30.0
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                self.server = GcsServer(host, port,
                                        persist_dir=self.replica_dir,
                                        leader_epoch=self.leader_epoch)
                self.server.start()
                break
            except OSError as e:  # port not yet released
                last = e
                time.sleep(0.1)
        else:
            raise RuntimeError(
                f"standby could not bind {host}:{port}: {last}")
        self.promoted.set()
        # Fencing: keep telling the old primary it is deposed until the
        # message lands (it may be alive but partitioned — the exact
        # split-brain case; when the partition heals, this or a raylet
        # report stamped with the new epoch fences it).
        threading.Thread(target=self._fence_old_primary, daemon=True,
                         name="gcs-fence").start()

    def _fence_old_primary(self):
        client = RetryableRpcClient(self.primary_address, deadline_s=2.0)
        try:
            while not self._stop.is_set():
                if self._testing_drop_polls:  # simulated partition covers
                    self._stop.wait(0.2)      # the fence path too
                    continue
                try:
                    if client.call("step_down", timeout=5.0,
                                   epoch=self.leader_epoch):
                        logger.info("old primary %s acknowledged step-down",
                                    self.primary_address)
                        return
                except Exception:  # noqa: BLE001 — still partitioned/dead
                    pass
                self._stop.wait(2.0)
        finally:
            client.close()

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
        if self.server is not None:
            self.server.stop()
        elif self._placeholder is not None:
            self._placeholder.stop()
