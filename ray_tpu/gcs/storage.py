"""GCS table storage — persistence behind the control plane.

Reference: ``src/ray/gcs/gcs_server/gcs_table_storage.h`` (typed tables over
a store client) + ``store_client/redis_store_client.h`` (the Redis-backed
implementation used for GCS fault tolerance). Redis is not part of this
image, so the store is a local append-only pickle log with write-time
flushing and open-time compaction — the recovery contract is the same: every
committed table mutation survives a GCS process crash and is replayed on
restart.

Layout: one log file holds all tables; records are ``(op, table, key,
value)`` pickle frames. Keys are bytes; values are plain dicts (pickled), so
replay needs no class imports.
"""

from __future__ import annotations

import logging
import os
import pickle
import shutil
import threading
from typing import Dict, Optional

logger = logging.getLogger(__name__)


class GcsTableStorage:
    """Append-log-backed map of table -> key -> record dict."""

    # rewrite the log once garbage (overwrites+deletes) passes this many frames
    _COMPACT_MIN_OPS = 10_000

    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        self._tables: Dict[str, Dict[bytes, dict]] = {}
        self._ops = 0
        # bumped on every compaction: a replica streaming the log by byte
        # offset must restart from 0 when the file is rewritten under it
        self._generation = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            self._replay()
            self._compact_locked()
        self._log = open(path, "ab")

    def _replay(self):
        size = os.path.getsize(self._path)
        stopped_at = size
        with open(self._path, "rb") as f:
            while True:
                try:
                    op, table, key, value = pickle.load(f)
                except EOFError:
                    break  # clean end of log
                except Exception:  # noqa: BLE001
                    # Torn tail write: everything before it is valid. A
                    # truncated frame's surviving opcodes can raise far more
                    # than UnpicklingError (ValueError, IndexError,
                    # AttributeError, ...), and any of them crashing startup
                    # would break recovery exactly when it is needed.
                    stopped_at = f.tell()
                    break
                t = self._tables.setdefault(table, {})
                if op == "put":
                    t[key] = value
                else:
                    t.pop(key, None)
                self._ops += 1
        if stopped_at < size:
            # Distinguish the expected torn TAIL (crash mid-append: only the
            # final frame is lost) from mid-log corruption, where everything
            # after the bad frame is dropped. Either way compaction will
            # rewrite the log from the replayed state, so preserve the
            # original for forensics before that happens.
            backup = self._path + ".corrupt"
            try:
                shutil.copyfile(self._path, backup)
            except OSError:
                backup = "<copy failed>"
            lost = size - stopped_at
            level = logger.error if lost > 256 else logger.warning
            level(
                "gcs table log %s: replay stopped at byte %d of %d "
                "(%d bytes unread, %d ops replayed); original preserved "
                "at %s", self._path, stopped_at, size, lost, self._ops,
                backup)

    def _compact_locked(self):
        tmp = self._path + ".compact"
        with open(tmp, "wb") as f:
            for table, records in self._tables.items():
                for key, value in records.items():
                    pickle.dump(("put", table, key, value), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)
        self._ops = sum(len(t) for t in self._tables.values())
        self._generation += 1

    def put(self, table: str, key: bytes, value: dict) -> None:
        with self._lock:
            self._tables.setdefault(table, {})[key] = value
            if self._log is None:
                return  # closed mid-shutdown: background tasks may still land
            pickle.dump(("put", table, key, value), self._log)
            self._log.flush()
            self._ops += 1
            self._maybe_compact()

    def delete(self, table: str, key: bytes) -> None:
        with self._lock:
            existed = self._tables.get(table, {}).pop(key, None) is not None
            if existed and self._log is not None:
                pickle.dump(("del", table, key, None), self._log)
                self._log.flush()
                self._ops += 1
                self._maybe_compact()

    def _maybe_compact(self):
        live = sum(len(t) for t in self._tables.values())
        if self._ops - live >= self._COMPACT_MIN_OPS:
            self._log.close()
            self._compact_locked()
            self._log = open(self._path, "ab")

    def read_chunk(self, offset: int = 0, generation: Optional[int] = None,
                   max_bytes: int = 1 << 20) -> dict:
        """Log-shipping read for a warm standby (gcs/failover.py): bytes
        from ``offset``, or a restart marker when the log was compacted
        since the replica's ``generation``. Every put/delete flushes, so
        the file is frame-aligned at all times."""
        with self._lock:
            if generation is not None and generation != self._generation:
                return {"generation": self._generation, "restart": True}
            try:
                with open(self._path, "rb") as f:
                    f.seek(offset)
                    data = f.read(max_bytes)
            except OSError:
                data = b""
            return {"generation": self._generation, "offset": offset,
                    "data": data}

    def get(self, table: str, key: bytes) -> Optional[dict]:
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def all(self, table: str) -> Dict[bytes, dict]:
        with self._lock:
            return dict(self._tables.get(table, {}))

    def close(self):
        with self._lock:
            if self._log is not None:
                self._log.close()
                self._log = None
