"""GCS client — typed accessors (reference: gcs/gcs_client/gcs_client.h, accessor.h).

Failover: pass ``standby_addresses`` (or set ``RT_GCS_STANDBY_ADDRS`` to a
comma-separated ``host:port`` list — the env route is how raylets and
workers inherit it without plumbing) and the client rotates to the next
address when the current one stays dead past the per-address retry
deadline. See :mod:`ray_tpu.gcs.failover`.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu.common import faults
from ray_tpu.common.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu.common.status import GcsDeposedError
from ray_tpu.rpc.pubsub import Subscriber
from ray_tpu.rpc.rpc import (RemoteMethodError, RetryableRpcClient, RpcClient,
                             RpcError, RpcMethodNotFound,
                             RpcRetriesExhausted)


def _standby_addresses_from_env() -> List[Tuple[str, int]]:
    raw = os.environ.get("RT_GCS_STANDBY_ADDRS", "")
    out = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        out.append((host, int(port)))
    return out


class GcsClient:
    def __init__(self, address: Tuple[str, int],
                 client_id: Optional[str] = None,
                 standby_addresses: Sequence[Tuple[str, int]] = ()):
        self.address = tuple(address)
        self.addresses = [self.address]
        for a in list(standby_addresses) or _standby_addresses_from_env():
            a = tuple(a)
            if a not in self.addresses:
                self.addresses.append(a)
        self._addr_i = 0
        # multi-address clients fail over instead of retrying one dead
        # address for the full reconnect window
        deadline = 15.0 if len(self.addresses) > 1 else None
        self._deadline_s = deadline
        self._rpc = RetryableRpcClient(self.address, deadline_s=deadline)
        self._subscriber: Optional[Subscriber] = None
        self._client_id = client_id or f"client-{id(self):x}"
        # fencing: the highest leader epoch this client has followed — a
        # server claiming a LOWER epoch is a stale/deposed leader and is
        # skipped during rotation (gcs/failover.py protocol)
        self.leader_epoch_seen = 0

    def _judge_leader_info(self, info) -> bool:
        """Shared verdict on a get_leader_info reply (None = probe failed:
        dead / legacy / unpromoted standby — pass, call-level retries sort
        those out)."""
        if not isinstance(info, dict):
            return True
        if info.get("deposed"):
            return False
        epoch = int(info.get("epoch", 0))
        if epoch < self.leader_epoch_seen:
            return False
        self.leader_epoch_seen = max(self.leader_epoch_seen, epoch)
        return True

    def _leader_acceptable(self, address) -> bool:
        """Fencing probe (blocking — caller threads only, never the IO
        loop; the loop path uses _leader_acceptable_async)."""
        probe = RpcClient(address)
        try:
            info = probe.call("get_leader_info", timeout=5.0)
        except Exception:  # noqa: BLE001
            return True
        finally:
            probe.close()
        return self._judge_leader_info(info)

    async def _leader_acceptable_async(self, address) -> bool:
        probe = RpcClient(address)
        try:
            info = await probe.call_async("get_leader_info", timeout=5.0)
        except Exception:  # noqa: BLE001
            return True
        finally:
            probe.close()
        return self._judge_leader_info(info)

    def _advance_addr(self):
        self._addr_i = (self._addr_i + 1) % len(self.addresses)
        self.address = self.addresses[self._addr_i]

    def _swap_rpc(self):
        self._rpc.close()
        self._rpc = RetryableRpcClient(self.address,
                                       deadline_s=self._deadline_s)
        if self._subscriber is not None:
            try:
                self._subscriber.close()
            except Exception:  # noqa: BLE001
                pass
            self._subscriber = None

    def _rotate(self):
        for _ in range(len(self.addresses)):
            self._advance_addr()
            if self._leader_acceptable(self.address):
                break
        self._swap_rpc()

    async def _rotate_async(self):
        """IO-loop-safe rotation: the fencing probe must await, not block
        the only event loop (raylet report loops rotate in-loop)."""
        for _ in range(len(self.addresses)):
            self._advance_addr()
            if await self._leader_acceptable_async(self.address):
                break
        self._swap_rpc()

    # Rotation triggers: RpcMethodNotFound = an unpromoted standby answered
    # ("not the leader" — rotate instantly, no retry window burned);
    # RpcRetriesExhausted = the address is transport-dead.  A plain per-call
    # RtTimeoutError (slow-but-alive primary) deliberately does NOT rotate —
    # tearing down the subscriber over one slow call would lose pubsub state
    # for no availability gain.
    _ROTATE_ON = (RpcMethodNotFound, RpcRetriesExhausted, RpcError)

    @staticmethod
    def _deposed(e: Exception) -> bool:
        return (isinstance(e, RemoteMethodError)
                and isinstance(e.cause, GcsDeposedError))

    # -- async passthrough for in-loop callers --
    async def call_async(self, method: str, **kwargs):
        last: Optional[Exception] = None
        for _ in range(len(self.addresses)):
            try:
                faults.fault_point("gcs.rpc.send")
                return await self._rpc.call_async(method, **kwargs)
            except faults.FaultInjected as e:
                # injected control-plane unreachability takes the exact
                # exit a burned reconnect window does: rotate to a standby
                # when there is one, else the typed transport-dead error
                last = RpcRetriesExhausted(f"gcs rpc {method} failed: {e}")
                if len(self.addresses) == 1:
                    raise last from e
                await self._rotate_async()
            except self._ROTATE_ON as e:
                last = e
                if len(self.addresses) == 1:
                    raise
                await self._rotate_async()
            except RemoteMethodError as e:
                if not self._deposed(e) or len(self.addresses) == 1:
                    raise
                last = e
                await self._rotate_async()
        raise last  # type: ignore[misc]

    def call(self, method: str, **kwargs):
        last: Optional[Exception] = None
        for _ in range(len(self.addresses)):
            try:
                faults.fault_point("gcs.rpc.send")
                return self._rpc.call(method, **kwargs)
            except faults.FaultInjected as e:
                last = RpcRetriesExhausted(f"gcs rpc {method} failed: {e}")
                if len(self.addresses) == 1:
                    raise last from e
                self._rotate()
            except self._ROTATE_ON as e:
                last = e
                if len(self.addresses) == 1:
                    raise
                self._rotate()
            except RemoteMethodError as e:
                if not self._deposed(e) or len(self.addresses) == 1:
                    raise
                last = e
                self._rotate()
        raise last  # type: ignore[misc]

    @property
    def subscriber(self) -> Subscriber:
        if self._subscriber is None:
            self._subscriber = Subscriber(self._client_id, self.address)
        return self._subscriber

    # -- nodes --
    def register_node(self, node_id: NodeID, address, resources: Dict[str, float],
                      labels: Dict[str, str], object_store_address: Optional[str] = None) -> dict:
        return self.call(
            "register_node", node_id=node_id.binary(), address=address,
            resources=resources, labels=labels, object_store_address=object_store_address,
        )

    def get_all_nodes(self) -> List[dict]:
        return self.call("get_all_nodes")

    # -- object location directory --
    def object_locations_update(self, updates: List[dict]) -> dict:
        """Push one owner-coalesced batch of location transitions
        (``{"op": "add"|"remove"|"spill", "object_id", "node_id",
        "address"?, "size"?}``)."""
        return self.call("object_locations_update", updates=updates)

    def get_object_locations(self, object_ids: List[bytes]) -> dict:
        """oid-hex -> [{node_id, address, spilled, size}] for every live
        copy the directory knows about."""
        return self.call("get_object_locations", object_ids=list(object_ids))

    def cluster_resources(self) -> dict:
        return self.call("get_cluster_resources")

    # -- jobs --
    def get_next_job_id(self) -> JobID:
        return JobID(self.call("get_next_job_id"))

    def register_job(self, job_id: JobID, driver_address=None, entrypoint: str = "") -> bool:
        return self.call("register_job", job_id=job_id.binary(),
                         driver_address=driver_address, entrypoint=entrypoint)

    def finish_job(self, job_id: JobID) -> bool:
        return self.call("finish_job", job_id=job_id.binary())

    # -- actors --
    def register_actor(self, creation_spec: bytes, actor_id: ActorID, job_id: JobID,
                       name: Optional[str] = None, namespace: str = "default",
                       max_restarts: int = 0) -> dict:
        return self.call(
            "register_actor", creation_spec=creation_spec, actor_id=actor_id.binary(),
            job_id=job_id.binary(), name=name, namespace=namespace, max_restarts=max_restarts,
        )

    def get_actor(self, actor_id: ActorID) -> Optional[dict]:
        return self.call("get_actor", actor_id=actor_id.binary())

    def get_actor_by_name(self, name: str, namespace: str = "default") -> Optional[dict]:
        return self.call("get_actor_by_name", name=name, namespace=namespace)

    def list_actors(self) -> List[dict]:
        return self.call("list_actors")

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> bool:
        return self.call("kill_actor", actor_id=actor_id.binary(), no_restart=no_restart)

    # -- placement groups --
    def create_placement_group(self, pg_id: PlacementGroupID, bundles: List[dict],
                               strategy: str, name: Optional[str] = None,
                               job_id: Optional[JobID] = None) -> dict:
        return self.call(
            "create_placement_group", pg_id=pg_id.binary(), bundles=bundles,
            strategy=strategy, name=name, job_id=job_id and job_id.binary(),
        )

    def remove_placement_group(self, pg_id: PlacementGroupID) -> bool:
        return self.call("remove_placement_group", pg_id=pg_id.binary())

    def get_placement_group(self, pg_id: PlacementGroupID) -> Optional[dict]:
        return self.call("get_placement_group", pg_id=pg_id.binary())

    def wait_placement_group_ready(self, pg_id: PlacementGroupID, timeout: float = 30.0) -> dict:
        return self.call("wait_placement_group_ready", pg_id=pg_id.binary(),
                         timeout_s=timeout, timeout=timeout + 5.0)

    def list_placement_groups(self) -> List[dict]:
        return self.call("list_placement_groups")

    # -- KV --
    def kv_put(self, namespace: str, key, value: bytes, overwrite: bool = True) -> bool:
        return self.call("kv_put", namespace=namespace, key=key, value=value, overwrite=overwrite)

    def kv_get(self, namespace: str, key) -> Optional[bytes]:
        return self.call("kv_get", namespace=namespace, key=key)

    def kv_del(self, namespace: str, key) -> bool:
        return self.call("kv_del", namespace=namespace, key=key)

    def kv_keys(self, namespace: str, prefix=b"") -> List[bytes]:
        return self.call("kv_keys", namespace=namespace, prefix=prefix)

    def close(self):
        if self._subscriber is not None:
            self._subscriber.close()
        self._rpc.close()
