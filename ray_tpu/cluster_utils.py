"""Multi-node test cluster on one machine (reference:
python/ray/cluster_utils.py:135 Cluster / add_node:202 / remove_node:286).

Runs one GCS plus N raylets in the current process (each raylet still forks
real worker subprocesses), which is how the reference tests multi-node
behavior on localhost.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.gcs.server import GcsServer
from ray_tpu.raylet.raylet import Raylet


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None,
                 persist_dir: Optional[str] = None):
        self.persist_dir = persist_dir
        self.gcs = GcsServer(persist_dir=persist_dir)
        self.gcs.start()
        self.raylets: List[Raylet] = []
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    def kill_gcs(self):
        """Simulate a GCS crash: stop the server, leave raylets running."""
        self.gcs.server.stop()
        self.gcs._stopped = True
        if self.gcs.storage is not None:
            self.gcs.storage.close()
        self.gcs.kv.close()

    def restart_gcs(self):
        """Bring the GCS back at the SAME address, recovering state from the
        persist log; surviving raylets re-register via their report loop."""
        addr = self.gcs.address
        self.gcs = GcsServer(host=addr[0], port=addr[1],
                             persist_dir=self.persist_dir)
        self.gcs.start()

    @property
    def address(self) -> str:
        return f"{self.gcs.address[0]}:{self.gcs.address[1]}"

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None) -> Raylet:
        node_resources = dict(resources or {})
        node_resources.setdefault("CPU", num_cpus)
        if num_tpus:
            node_resources["TPU"] = num_tpus
        raylet = Raylet(self.gcs.address, resources=node_resources, labels=labels)
        raylet.start()
        self.raylets.append(raylet)
        return raylet

    def remove_node(self, raylet: Raylet, graceful: bool = False):
        """Kill a node (ungraceful = simulate crash: workers die, GCS finds out
        via health checks)."""
        raylet.stop()
        self.raylets.remove(raylet)
        if graceful:
            try:
                self.gcs.server and None
                from ray_tpu.gcs.client import GcsClient

                c = GcsClient(self.gcs.address)
                c.call("unregister_node", node_id=raylet.node_id.binary())
                c.close()
            except Exception:  # noqa: BLE001
                pass

    def wait_for_nodes(self, count: Optional[int] = None, timeout: float = 30.0):
        from ray_tpu.gcs.client import GcsClient

        want = count if count is not None else len(self.raylets)
        c = GcsClient(self.gcs.address)
        deadline = time.monotonic() + timeout
        try:
            while time.monotonic() < deadline:
                alive = [n for n in c.get_all_nodes() if n["alive"]]
                if len(alive) >= want:
                    return True
                time.sleep(0.1)
            return False
        finally:
            c.close()

    def shutdown(self):
        for r in list(self.raylets):
            r.stop()
        self.raylets.clear()
        self.gcs.stop()
