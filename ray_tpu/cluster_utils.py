"""Multi-node test cluster on one machine (reference:
python/ray/cluster_utils.py:135 Cluster / add_node:202 / remove_node:286).

Two shapes, mirroring ``ray_tpu.init``'s deployment shapes:

- default: one GCS plus N raylets in the current process (each raylet
  still forks real worker subprocesses), which is how the reference tests
  multi-node behavior on localhost.
- ``control_plane_procs=True``: the GCS and every raylet run as dedicated
  OS processes (ray_tpu/control_plane.py) — real process boundaries for
  crash/failover tests, and the deployment shape the round-9 perf work
  benchmarks.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from ray_tpu.gcs.server import GcsServer
from ray_tpu.raylet.raylet import Raylet


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None,
                 persist_dir: Optional[str] = None,
                 control_plane_procs: bool = False):
        self.persist_dir = persist_dir
        self.control_plane_procs = control_plane_procs
        self.raylets: List[Raylet] = []   # in-process shape
        self.raylet_procs: List = []      # multi-process shape
        self._raylet_infos: List[dict] = []
        if control_plane_procs:
            from ray_tpu.control_plane import launch_gcs

            self.session_dir = (
                f"/tmp/rt/cluster_{os.getpid()}_{id(self) & 0xffffff:x}")
            self.gcs = None
            self.gcs_proc, self._gcs_address = launch_gcs(
                self.session_dir, persist_dir=persist_dir)
        else:
            self.gcs = GcsServer(persist_dir=persist_dir)
            self.gcs.start()
            self.gcs_proc = None
            self._gcs_address = self.gcs.address
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def gcs_address(self):
        return self._gcs_address

    def kill_gcs(self):
        """Simulate a GCS crash: stop the server, leave raylets running."""
        if self.control_plane_procs:
            self.gcs_proc.kill()
            self.gcs_proc.proc.wait(timeout=10)
            return
        self.gcs.server.stop()
        self.gcs._stopped = True
        if self.gcs.storage is not None:
            self.gcs.storage.close()
        self.gcs.kv.close()

    def restart_gcs(self):
        """Bring the GCS back at the SAME address, recovering state from the
        persist log; surviving raylets re-register via their report loop."""
        addr = self._gcs_address
        if self.control_plane_procs:
            from ray_tpu.control_plane import launch_gcs

            self.gcs_proc, self._gcs_address = launch_gcs(
                self.session_dir, persist_dir=self.persist_dir,
                host=addr[0], port=addr[1])
            return
        self.gcs = GcsServer(host=addr[0], port=addr[1],
                             persist_dir=self.persist_dir)
        self.gcs.start()

    @property
    def address(self) -> str:
        return f"{self._gcs_address[0]}:{self._gcs_address[1]}"

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None):
        node_resources = dict(resources or {})
        node_resources.setdefault("CPU", num_cpus)
        if num_tpus:
            node_resources["TPU"] = num_tpus
        if self.control_plane_procs:
            from ray_tpu.control_plane import launch_raylet

            proc, info = launch_raylet(
                self._gcs_address,
                os.path.join(self.session_dir,
                             f"node{len(self.raylet_procs)}"),
                resources=node_resources, labels=labels)
            self.raylet_procs.append(proc)
            self._raylet_infos.append(info)
            return proc
        raylet = Raylet(self._gcs_address, resources=node_resources,
                        labels=labels)
        raylet.start()
        self.raylets.append(raylet)
        return raylet

    def remove_node(self, raylet, graceful: bool = False):
        """Kill a node (ungraceful = simulate crash: workers die, GCS finds
        out via health checks)."""
        if self.control_plane_procs:
            idx = self.raylet_procs.index(raylet)
            info = self._raylet_infos.pop(idx)
            self.raylet_procs.remove(raylet)
            if graceful:
                raylet.stop()
            else:
                raylet.kill()
            node_id_bin = bytes.fromhex(info["node_id_hex"])
        else:
            raylet.stop()
            self.raylets.remove(raylet)
            node_id_bin = raylet.node_id.binary() if graceful else None
        if graceful and node_id_bin is not None:
            try:
                from ray_tpu.gcs.client import GcsClient

                c = GcsClient(self._gcs_address)
                c.call("unregister_node", node_id=node_id_bin)
                c.close()
            except Exception:  # noqa: BLE001
                pass

    def wait_for_nodes(self, count: Optional[int] = None, timeout: float = 30.0):
        from ray_tpu.gcs.client import GcsClient

        want = count if count is not None else (
            len(self.raylet_procs) if self.control_plane_procs
            else len(self.raylets))
        c = GcsClient(self._gcs_address)
        deadline = time.monotonic() + timeout
        try:
            while time.monotonic() < deadline:
                alive = [n for n in c.get_all_nodes() if n["alive"]]
                if len(alive) >= want:
                    return True
                time.sleep(0.1)
            return False
        finally:
            c.close()

    def shutdown(self):
        if self.control_plane_procs:
            for p in list(self.raylet_procs):
                p.stop()
            self.raylet_procs.clear()
            self._raylet_infos.clear()
            if self.gcs_proc is not None:
                self.gcs_proc.stop()
            return
        for r in list(self.raylets):
            r.stop()
        self.raylets.clear()
        self.gcs.stop()
