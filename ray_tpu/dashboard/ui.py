"""Dashboard single-page UI (no build step).

Reference: ``python/ray/dashboard/client/`` is a React app compiled by
webpack; the capability it provides — live jobs/actors/tasks/serve/node
views over the REST surface — is delivered here as one vanilla-JS page
served by the dashboard process itself (scope decision recorded in
README "Scope decisions"). Views poll the same /api endpoints external
tooling uses, so the page is also living documentation of the API.
"""

INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<meta charset="utf-8">
<style>
 :root { --bd:#d8d8d8; --bg:#fafafa; --acc:#2563eb; --bad:#dc2626;
         --ok:#16a34a; }
 body { font-family: system-ui, sans-serif; margin:0; color:#1f2328; }
 header { display:flex; align-items:baseline; gap:1.2rem; padding:.7rem 1.2rem;
          border-bottom:1px solid var(--bd); background:var(--bg); }
 header h1 { font-size:1.05rem; margin:0; }
 nav a { margin-right:.9rem; text-decoration:none; color:#555;
         font-size:.9rem; padding:.15rem 0; }
 nav a.active { color:var(--acc); border-bottom:2px solid var(--acc); }
 main { padding:1rem 1.2rem; }
 table { border-collapse:collapse; margin-top:.5rem; width:100%; }
 td,th { border:1px solid var(--bd); padding:.3rem .55rem; font-size:.82rem;
         text-align:left; vertical-align:top; }
 th { background:var(--bg); }
 .pill { display:inline-block; padding:0 .45rem; border-radius:.6rem;
         font-size:.75rem; color:#fff; }
 .ALIVE,.RUNNING,.SUCCEEDED,.FINISHED,.CREATED,.ok { background:var(--ok); }
 .DEAD,.FAILED,.bad { background:var(--bad); }
 .PENDING,.RESTARTING,.STOPPED,.warn { background:#d97706; }
 .cards { display:flex; gap:1rem; flex-wrap:wrap; margin-bottom:1rem; }
 .card { border:1px solid var(--bd); border-radius:.5rem; padding:.6rem 1rem;
         min-width:8rem; background:var(--bg); }
 .card .v { font-size:1.4rem; font-weight:600; }
 .card .k { font-size:.75rem; color:#666; }
 pre { background:#f6f6f6; padding: .6rem; overflow:auto; font-size:.78rem; }
 svg { background:var(--bg); border:1px solid var(--bd); }
 input,button,textarea { font:inherit; padding:.25rem .5rem; }
 .muted { color:#777; font-size:.78rem; }
</style></head>
<body>
<header>
 <h1>ray_tpu</h1>
 <nav id="nav"></nav>
 <span id="uptime" class="muted"></span>
</header>
<main id="main">loading…</main>
<script>
const VIEWS = ["overview","nodes","actors","pgs","jobs","serve","tasks",
               "metrics","logs"];
const $ = (s) => document.querySelector(s);
const esc = (s) => String(s).replace(/[&<>"']/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const pill = (s) => `<span class="pill ${esc(s)}">${esc(s)}</span>`;
const fmtB = (b) => b > 1<<30 ? (b/(1<<30)).toFixed(1)+" GiB"
  : b > 1<<20 ? (b/(1<<20)).toFixed(1)+" MiB" : b + " B";
const api = async (p) => (await fetch("/api/"+p)).json();
let timer = null;

function nav() {
  const cur = location.hash.slice(1) || "overview";
  $("#nav").innerHTML = VIEWS.map(v =>
    `<a href="#${v}" class="${v===cur?"active":""}">${v}</a>`).join("");
  return cur;
}

function table(rows, cols) {
  if (!rows.length) return "<p class='muted'>none</p>";
  return "<table><tr>" + cols.map(c=>`<th>${c[0]}</th>`).join("") + "</tr>" +
    rows.map(r => "<tr>" + cols.map(c=>`<td>${c[1](r)}</td>`).join("")
             + "</tr>").join("") + "</table>";
}

const R = {
 async overview() {
  const o = await api("overview"), v = await api("version");
  $("#uptime").textContent = "up " + Math.round(v.uptime_s) + "s";
  const res = o.resources.total || {}, avail = o.resources.available || {};
  const card = (k, val) =>
    `<div class="card"><div class="v">${val}</div><div class="k">${k}</div></div>`;
  return `<div class="cards">` +
    card("nodes", `${o.nodes_alive}/${o.nodes_total}`) +
    card("actors alive", `${o.actors_alive}/${o.actors_total}`) +
    card("CPU used", `${((res.CPU||0)-(avail.CPU||0)).toFixed(1)}/${res.CPU||0}`) +
    card("TPU used", `${((res.TPU||0)-(avail.TPU||0)).toFixed(1)}/${res.TPU||0}`) +
    card("jobs", o.jobs.length) + `</div>` +
    "<h2>jobs</h2>" + table(o.jobs, [
      ["id", j=>esc(j.submission_id)], ["status", j=>pill(j.status)],
      ["entrypoint", j=>`<code>${esc(j.entrypoint||"")}</code>`]]);
 },
 async nodes() {
  const ns = await api("nodes");
  return table(ns, [
    ["node", n=>`<code>${esc(n.node_id.slice(0,12))}</code>`],
    ["addr", n=>esc(n.address.join(":"))],
    ["state", n=>pill(n.alive?"ALIVE":"DEAD")],
    ["resources", n=>esc(JSON.stringify(n.resources))],
    ["mem", n=>n.stats&&n.stats.mem_total_bytes?
       fmtB(n.stats.mem_used_bytes)+" / "+fmtB(n.stats.mem_total_bytes):""],
    ["load1m", n=>n.stats&&n.stats.cpu_load_1m!=null?
       n.stats.cpu_load_1m.toFixed(2):""],
    ["workers", n=>n.stats?n.stats.num_workers:""],
    ["pending leases", n=>n.stats?n.stats.num_pending_leases:""]]);
 },
 async actors() {
  const as = await api("actors");
  return table(as, [
    ["actor", a=>`<code>${esc((a.actor_id||"").slice(0,12))}</code>`],
    ["name", a=>esc(a.name||"")], ["state", a=>pill(a.state)],
    ["node", a=>`<code>${esc((a.node_id||"").slice(0,12))}</code>`],
    ["restarts", a=>a.num_restarts||0],
    ["death", a=>esc(a.death_cause||"")]]);
 },
 async pgs() {
  const ps = await api("placement_groups");
  return table(ps, [
    ["pg", p=>`<code>${esc((p.pg_id||"").slice(0,12))}</code>`],
    ["name", p=>esc(p.name||"")], ["state", p=>pill(p.state)],
    ["strategy", p=>esc(p.strategy)],
    ["bundles", p=>esc(JSON.stringify(p.bundles))]]);
 },
 async jobs() {
  const js = await api("jobs/");
  window.showLogs = async (id) => {
    const r = await fetch(`/api/jobs/${id}/logs`);
    $("#joblog").textContent = await r.text();
  };
  return `<form onsubmit="event.preventDefault();
      fetch('/api/jobs/',{method:'POST',
        headers:{'content-type':'application/json'},
        body:JSON.stringify({entrypoint:this.ep.value})})
      .then(()=>render());">
    <input name="ep" size="60" placeholder="python my_script.py">
    <button>submit job</button></form>` +
    table(js, [
      ["id", j=>`<code>${esc(j.submission_id)}</code>`],
      ["status", j=>pill(j.status)],
      ["entrypoint", j=>`<code>${esc(j.entrypoint||"")}</code>`],
      ["logs", j=>`<a href="javascript:showLogs('${esc(j.submission_id)}')">view</a>`]]) +
    `<pre id="joblog"></pre>`;
 },
 async serve() {
  const s = await api("serve");
  const apps = Object.entries(s.apps||{}).map(([name, a]) =>
    ({name, ...a}));
  return (s.updated_at ?
      `<p class="muted">controller heartbeat ${Math.round(Date.now()/1000 - s.updated_at)}s ago</p>`
      : "<p class='muted'>no serve controller running</p>") +
    table(apps, [
      ["app", a=>esc(a.name)],
      ["replicas", a=>`${a.running_replicas}/${a.target_replicas}`],
      ["autoscaling", a=>a.autoscaling?"yes":"no"],
      ["health", a=>pill(a.running_replicas>=a.target_replicas?"ok":"warn")]]);
 },
 async tasks() {
  const evs = await api("task_events?limit=200");
  return `<p class="muted">latest ${evs.length} task state events
    (<a href="/api/task_events?limit=10000">raw</a>; chrome timeline via
    <code>ray_tpu.timeline()</code>)</p>` +
    table(evs.slice().reverse(), [
      ["task", e=>`<code>${esc((e.task_id||"").slice(0,12))}</code>`],
      ["name", e=>esc(e.name||"")], ["state", e=>pill(e.state||"")],
      ["node", e=>`<code>${esc((e.node_id||"").slice(0,10))}</code>`],
      ["duration", e=>e.end_ts?((e.end_ts-e.start_ts)*1000).toFixed(1)+" ms":""],
      ["finished", e=>e.end_ts?new Date(e.end_ts*1000).toLocaleTimeString():""]]);
 },
 async metrics() {
  const h = await api("metrics/history");
  const chart = (key, color) => {
    if (!h.length) return "";
    const w=560, ht=120, max=Math.max(1, ...h.map(p=>p[key]||0));
    const pts = h.map((p,i)=>`${(i/(h.length-1||1)*w).toFixed(1)},` +
      `${(ht-(p[key]||0)/max*ht).toFixed(1)}`).join(" ");
    return `<div><span class="muted">${key} (max ${max.toFixed(1)})</span><br>
      <svg width="${w}" height="${ht}"><polyline fill="none"
      stroke="${color}" stroke-width="1.5" points="${pts}"/></svg></div>`;
  };
  return chart("cpu_used","#2563eb") + chart("tpu_used","#dc2626") +
    chart("actors_alive","#16a34a") + chart("nodes_alive","#d97706") +
    `<p class="muted">Prometheus exposition at <a href="/api/metrics">
     /api/metrics</a>; scrape discovery at
     <a href="/api/prometheus_sd">/api/prometheus_sd</a>; generate
     Prometheus + Grafana configs with
     <code>python -m ray_tpu metrics-config</code></p>`;
 },
 async logs() {
  const ls = await api("logs");
  window.showLog = async (n) => {
    const r = await fetch(`/api/logs/${n}?tail=500`);
    $("#logview").textContent = await r.text();
  };
  return table(ls, [
    ["file", l=>`<a href="javascript:showLog('${esc(l.name)}')">${esc(l.name)}</a>`],
    ["size", l=>fmtB(l.size_bytes)]]) + `<pre id="logview"></pre>`;
 },
};

async function render() {
  const view = nav();
  try { $("#main").innerHTML = await R[view](); }
  catch (e) { $("#main").innerHTML = `<p class="bad pill">error</p>
    <pre>${esc(e)}</pre>`; }
}
window.addEventListener("hashchange", render);
render();
timer = setInterval(() => {
  const v = location.hash.slice(1) || "overview";
  // don't clobber the log viewers mid-read
  if (v !== "logs" && v !== "jobs") render();
}, 4000);
</script>
</body></html>
"""
