"""Dashboard: cluster-state REST API + job submission endpoint + web page.

Reference: ``python/ray/dashboard/`` (dashboard head, state API routes,
job_head.py REST handlers).
"""

from .dashboard import Dashboard

__all__ = ["Dashboard"]
