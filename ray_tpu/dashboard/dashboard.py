"""Dashboard head — REST API over GCS state + job submission + HTML page.

Reference: ``python/ray/dashboard/head.py`` (DashboardHead hosting module
routes), ``modules/job/job_head.py`` (the job REST surface mirrored here),
``modules/node/`` + ``modules/actor/`` (state routes), ``modules/
reporter/`` (Prometheus metrics). One asyncio HTTP server in the head
process; no separate agent daemons — the GCS already aggregates node state
and task events, so every route is a thin read of the control plane.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional, Tuple

from ray_tpu._version import __version__
from ray_tpu.gcs.client import GcsClient
from ray_tpu.job.job_manager import JobManager
from ray_tpu.rpc.rpc import IoContext
from ray_tpu.util.http import (HttpRequest, HttpResponse, HttpServer,
                               StreamResponse)

logger = logging.getLogger(__name__)

DEFAULT_DASHBOARD_PORT = 8265


class Dashboard:
    def __init__(self, gcs_address: Tuple[str, int], session_dir: str,
                 host: str = "127.0.0.1", port: int = 0):
        self._gcs_address = tuple(gcs_address)
        self._gcs = GcsClient(self._gcs_address, client_id="dashboard")
        self._session_dir = session_dir
        self.job_manager = JobManager(self._gcs_address, session_dir)
        self._http = HttpServer(host, port)
        self._io = IoContext.current()
        self._started = time.time()
        import collections as _collections

        self._history = _collections.deque(maxlen=720)  # ~1h at 5s period
        self._history_period = 5.0
        self._history_stopped = False
        # per-node system gauges (reference: per-node reporter agents —
        # here the raylets ship stats with their resource reports and the
        # dashboard re-exports them with a node_id label)
        from ray_tpu.util.metrics import Gauge

        self._node_gauges = {
            k: Gauge(f"rt_node_{k}", f"per-node {k.replace('_', ' ')}",
                     tag_keys=("node_id",))
            for k in ("mem_used_bytes", "mem_total_bytes", "cpu_load_1m",
                      "num_workers", "num_pending_leases",
                      "object_store_capacity_bytes",
                      "object_store_used_bytes",
                      "object_store_num_objects")
        }
        self._register_routes()

    @property
    def address(self) -> Optional[Tuple[str, int]]:
        return self._http.address

    @property
    def url(self) -> str:
        host, port = self._http.address
        return f"http://{host}:{port}"

    def start(self):
        self._io.run(self._http.start(), timeout=10)
        self._io.spawn_threadsafe(self._history_loop())
        logger.info("dashboard serving at %s", self.url)

    def stop(self):
        self._history_stopped = True
        try:
            self._io.run(self._http.stop(), timeout=5)
        except Exception:  # noqa: BLE001
            pass
        self.job_manager.close()
        self._gcs.close()

    async def _history_loop(self):
        """Metrics time series (reference: dashboard/modules/metrics —
        Grafana provisioning; minimum-bar equivalent here is an in-memory
        ring of cluster snapshots served at /api/metrics/history and
        charted on the index page)."""
        while not self._history_stopped:
            try:
                res = await self._gcs.call_async("get_cluster_resources")
                actors = await self._gcs.call_async("list_actors")
                nodes = await self._gcs.call_async("get_all_nodes")
                total = res.get("total", {})
                avail = res.get("available", {})
                self._history.append({
                    "ts": time.time(),
                    "cpu_used": float(total.get("CPU", 0.0)
                                      - avail.get("CPU", 0.0)),
                    "cpu_total": float(total.get("CPU", 0.0)),
                    "tpu_used": float(total.get("TPU", 0.0)
                                      - avail.get("TPU", 0.0)),
                    "tpu_total": float(total.get("TPU", 0.0)),
                    "actors_alive": sum(
                        1 for a in actors if a["state"] == "ALIVE"),
                    "nodes_alive": sum(1 for n in nodes if n["alive"]),
                })
                for n in nodes:
                    tags = {"node_id": n["node_id"].hex()}
                    for k, g in self._node_gauges.items():
                        v = (n.get("stats") or {}).get(k)
                        if v is not None:
                            g.set(float(v), tags=tags)
            except Exception:  # noqa: BLE001 — GCS restarting etc.
                pass
            await asyncio.sleep(self._history_period)

    # ---------------------------------------------------------------- routes
    def _register_routes(self):
        r = self._http.route
        r("GET", "/", self._index)
        r("GET", "/api/version", self._version)
        r("GET", "/api/overview", self._overview)
        r("GET", "/api/nodes", self._nodes)
        r("GET", "/api/actors", self._actors)
        r("GET", "/api/placement_groups", self._pgs)
        r("GET", "/api/cluster_resources", self._resources)
        r("GET", "/api/task_events", self._task_events)
        r("GET", "/api/metrics", self._metrics)
        r("GET", "/api/metrics/history", self._metrics_history)
        r("GET", "/api/serve", self._serve_status)
        # declarative deploy (reference: PUT /api/serve/applications/ on
        # the dashboard agent + serve/schema.py ServeDeploySchema)
        r("PUT", "/api/serve/applications", self._serve_apply)
        r("GET", "/api/serve/applications", self._serve_declared)
        # Prometheus HTTP service discovery (reference:
        # dashboard/modules/metrics prometheus config); point
        # `http_sd_configs` here and every scrape target is enumerated
        r("GET", "/api/prometheus_sd", self._prometheus_sd)
        # job REST surface (reference job_head.py)
        r("POST", "/api/jobs/", self._submit_job)
        r("GET", "/api/jobs/", self._list_jobs)
        r("GET", "/api/jobs/{sid}", self._get_job)
        r("POST", "/api/jobs/{sid}/stop", self._stop_job)
        r("DELETE", "/api/jobs/{sid}", self._delete_job)
        r("GET", "/api/jobs/{sid}/logs", self._job_logs)
        r("GET", "/api/jobs/{sid}/logs/tail", self._job_logs_tail)
        # session log files (reference: dashboard log module / log_monitor)
        r("GET", "/api/logs", self._list_logs)
        r("GET", "/api/logs/{name}", self._get_log)

    # ------------------------------------------------------------- handlers
    async def _version(self, _req: HttpRequest):
        return {"version": __version__, "uptime_s": time.time() - self._started}

    async def _nodes(self, _req: HttpRequest):
        nodes = await self._gcs.call_async("get_all_nodes")
        for n in nodes:
            n["node_id"] = n["node_id"].hex()
        return nodes

    async def _actors(self, _req: HttpRequest):
        return await self._gcs.call_async("list_actors")

    async def _pgs(self, _req: HttpRequest):
        return await self._gcs.call_async("list_placement_groups")

    async def _resources(self, _req: HttpRequest):
        return await self._gcs.call_async("get_cluster_resources")

    async def _task_events(self, req: HttpRequest):
        limit = int(req.query.get("limit", "1000"))
        return await self._gcs.call_async("get_task_events", limit=limit)

    async def _overview(self, _req: HttpRequest):
        nodes = await self._gcs.call_async("get_all_nodes")
        actors = await self._gcs.call_async("list_actors")
        res = await self._gcs.call_async("get_cluster_resources")
        jobs = await asyncio.to_thread(self.job_manager.list_jobs)
        return {
            "nodes_alive": sum(1 for n in nodes if n["alive"]),
            "nodes_total": len(nodes),
            "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
            "actors_total": len(actors),
            "resources": res,
            "jobs": [j.public_view() for j in jobs],
        }

    async def _metrics(self, _req: HttpRequest):
        from ray_tpu.util.metrics import prometheus_text

        return HttpResponse(prometheus_text(),
                            content_type="text/plain; version=0.0.4")

    async def _metrics_history(self, req: HttpRequest):
        limit = int(req.query.get("limit", "720"))
        return list(self._history)[-limit:]

    async def _serve_status(self, _req: HttpRequest):
        """Serve view: the controller drops its app table into GCS KV
        every reconcile pass (serve/controller.py _publish_status)."""
        import json as _json

        raw = await self._gcs.call_async("kv_get", namespace="serve",
                                         key=b"status")
        if not raw:
            return {"apps": {}, "updated_at": None}
        return _json.loads(raw)

    async def _serve_apply(self, req: HttpRequest):
        """PUT a declarative app spec: validated, then persisted in the
        GCS KV where the Serve controller reconciles onto it.  A spec PUT
        before Serve starts applies when it does (the KV outlives every
        Serve component)."""
        import json as _json

        from ray_tpu.serve import schema
        from ray_tpu.util.http import HttpResponse

        try:
            doc = schema.make_config_doc(req.json())
        except (schema.ServeConfigError, ValueError) as e:
            # ValueError covers a non-JSON body (json.JSONDecodeError):
            # both are client errors, not server faults
            return HttpResponse({"error": str(e)}, 400)
        await self._gcs.call_async(
            "kv_put", namespace=schema.KV_NAMESPACE,
            key=schema.KV_CONFIG_KEY,
            value=_json.dumps(doc).encode(), overwrite=True)
        return {"ok": True, "version": doc["version"]}

    async def _serve_declared(self, _req: HttpRequest):
        """GET the declared spec + the controller's last apply status +
        live app table."""
        import json as _json

        from ray_tpu.serve import schema

        out = {}
        for field, key in (("config", schema.KV_CONFIG_KEY),
                           ("apply_status", schema.KV_APPLY_STATUS_KEY),
                           ("live", b"status")):
            raw = await self._gcs.call_async(
                "kv_get", namespace=schema.KV_NAMESPACE, key=key)
            out[field] = _json.loads(raw) if raw else None
        return out

    async def _prometheus_sd(self, _req: HttpRequest):
        host, port = self._http.address
        return [{
            "targets": [f"{host}:{port}"],
            "labels": {"job": "ray_tpu", "component": "dashboard"},
        }]

    # job handlers ---------------------------------------------------------
    async def _submit_job(self, req: HttpRequest):
        body = req.json()
        if not body or not body.get("entrypoint"):
            return HttpResponse({"error": "entrypoint is required"}, 400)
        try:
            sid = await asyncio.to_thread(
                self.job_manager.submit_job,
                entrypoint=body["entrypoint"],
                submission_id=body.get("submission_id"),
                runtime_env=body.get("runtime_env"),
                metadata=body.get("metadata"),
            )
        except ValueError as e:
            return HttpResponse({"error": str(e)}, 409)
        return HttpResponse({"submission_id": sid}, 201)

    async def _list_jobs(self, _req: HttpRequest):
        jobs = await asyncio.to_thread(self.job_manager.list_jobs)
        return [j.public_view() for j in jobs]

    async def _get_job(self, req: HttpRequest):
        info = await asyncio.to_thread(
            self.job_manager.get_job_info, req.path_params["sid"])
        if info is None:
            return HttpResponse({"error": "no such job"}, 404)
        return info.public_view()

    async def _stop_job(self, req: HttpRequest):
        ok = await asyncio.to_thread(
            self.job_manager.stop_job, req.path_params["sid"])
        return {"stopped": ok}

    async def _delete_job(self, req: HttpRequest):
        ok = await asyncio.to_thread(
            self.job_manager.delete_job, req.path_params["sid"])
        return {"deleted": ok}

    async def _job_logs(self, req: HttpRequest):
        text = await asyncio.to_thread(
            self.job_manager.get_job_logs, req.path_params["sid"])
        return HttpResponse(text)

    async def _job_logs_tail(self, req: HttpRequest):
        return StreamResponse(
            self.job_manager.tail_logs(req.path_params["sid"]))

    # log handlers ---------------------------------------------------------
    async def _list_logs(self, _req: HttpRequest):
        import os

        def scan():
            out = []
            for fname in sorted(os.listdir(self._session_dir)):
                path = os.path.join(self._session_dir, fname)
                if os.path.isfile(path) and fname.endswith(".log"):
                    out.append({"name": fname,
                                "size_bytes": os.path.getsize(path)})
            return out

        return await asyncio.to_thread(scan)

    async def _get_log(self, req: HttpRequest):
        import os

        name = req.path_params["name"]
        if "/" in name or ".." in name:
            return HttpResponse({"error": "bad log name"}, 400)
        path = os.path.join(self._session_dir, name)
        if not os.path.isfile(path):
            return HttpResponse({"error": "no such log"}, 404)
        tail = int(req.query.get("tail", "0") or 0)

        def read():
            with open(path, "r", errors="replace") as f:
                text = f.read()
            if tail > 0:
                text = "\n".join(text.splitlines()[-tail:])
            return text

        return HttpResponse(await asyncio.to_thread(read),
                            content_type="text/plain")

    async def _index(self, _req: HttpRequest):
        from ray_tpu.dashboard.ui import INDEX_HTML

        return HttpResponse(INDEX_HTML, content_type="text/html")
