"""RuntimeEnvAgent — materializes runtime envs on a node.

Reference: ``python/ray/_private/runtime_env/agent/runtime_env_agent.py:165``
(GetOrCreateRuntimeEnv / DeleteRuntimeEnvIfPossible with per-env refcounts
and a URI cache). Here the agent lives inside the raylet process (no
separate daemon needed — setup is file staging, not package downloads) and
returns a :class:`WorkerEnvContext` the worker pool applies at fork time.
"""

from __future__ import annotations

import importlib.util
import logging
import os
import shutil
import threading
import zipfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .runtime_env import RuntimeEnvError, env_hash, validate

logger = logging.getLogger(__name__)


@dataclass
class WorkerEnvContext:
    """Everything the worker fork needs to run inside the env."""

    env_key: Optional[str] = None
    env_vars: Dict[str, str] = field(default_factory=dict)
    cwd: Optional[str] = None
    pythonpath_prepend: List[str] = field(default_factory=list)

    def apply(self, base_env: Dict[str, str]) -> Dict[str, str]:
        out = dict(base_env)
        out.update(self.env_vars)
        if self.pythonpath_prepend:
            existing = out.get("PYTHONPATH", "")
            parts = list(self.pythonpath_prepend)
            if existing:
                parts.append(existing)
            out["PYTHONPATH"] = os.pathsep.join(parts)
        return out


class RuntimeEnvAgent:
    def __init__(self, session_dir: str):
        self._root = os.path.join(session_dir, "runtime_envs")
        os.makedirs(self._root, exist_ok=True)
        self._lock = threading.Lock()
        self._cache: Dict[str, WorkerEnvContext] = {}
        self._refs: Dict[str, int] = {}

    def get_or_create(self, env: Optional[dict]) -> WorkerEnvContext:
        """Materialize (or fetch cached) the env. Raises RuntimeEnvError on
        anything that cannot be satisfied — the caller fails the lease, not
        the node. References are NOT taken here: a holder (worker process,
        job driver) calls :meth:`acquire` when it starts using the env and
        :meth:`release` when it exits."""
        if not env:
            return WorkerEnvContext()
        validate(env)
        key = env_hash(env)
        with self._lock:
            ctx = self._cache.get(key)
            if ctx is not None:
                return ctx
        ctx = self._materialize(key, env)
        with self._lock:
            self._cache[key] = ctx
            self._refs.setdefault(key, 0)
        return ctx

    def acquire(self, key: Optional[str]) -> None:
        """One live holder (a forked worker / running job driver)."""
        if key is None:
            return
        with self._lock:
            self._refs[key] = self._refs.get(key, 0) + 1

    def release(self, key: Optional[str]) -> None:
        """Drop one holder; unreferenced envs stay cached (cheap disk)
        until evict_unused() — matching the reference's soft URI cache."""
        if key is None:
            return
        with self._lock:
            if key in self._refs:
                self._refs[key] = max(0, self._refs[key] - 1)

    def evict_unused(self) -> int:
        """Delete staged files of envs with zero references. Returns count."""
        n = 0
        with self._lock:
            for key in [k for k, r in self._refs.items() if r == 0]:
                self._cache.pop(key, None)
                self._refs.pop(key, None)
                shutil.rmtree(os.path.join(self._root, key),
                              ignore_errors=True)
                n += 1
        return n

    # ------------------------------------------------------------- internals
    def _materialize(self, key: str, env: dict) -> WorkerEnvContext:
        """Stage-then-rename: the env is built in a private tmp dir and
        atomically renamed to its content-addressed location. The key hashes
        every file's (size, mtime) — same key ⇒ same content — so an
        existing staged dir is ALWAYS safe to reuse, never deleted/rebuilt:
        concurrent materializations (two threads, or the raylet's and the
        job manager's agent sharing one session dir) race benignly on the
        rename, and live workers whose cwd is inside a staged dir never
        have it pulled out from under them."""
        reqs = env.get("pip") or []
        find_links = env.get("pip_find_links") or []
        if reqs and not find_links:
            # no package source (zero-egress image): gate on importability
            self._check_pip(reqs)
        stage = os.path.join(self._root, key)
        ready = os.path.join(stage, ".ready")
        if not os.path.exists(ready):
            tmp = f"{stage}.tmp.{os.getpid()}.{threading.get_ident()}"
            os.makedirs(tmp, exist_ok=True)
            try:
                wd = env.get("working_dir")
                if wd is not None:
                    self._stage_path(wd, os.path.join(tmp, "working_dir"))
                for i, mod in enumerate(env.get("py_modules") or []):
                    self._stage_path(mod, os.path.join(tmp, f"py_module_{i}"))
                if reqs and find_links:
                    self._pip_install(env, reqs, find_links,
                                      os.path.join(tmp, "pylibs"))
                with open(os.path.join(tmp, ".ready"), "w") as f:
                    f.write(key)
                try:
                    os.rename(tmp, stage)
                    logger.info("runtime env %s staged at %s", key, stage)
                except OSError:
                    # another materializer won the rename: reuse theirs
                    shutil.rmtree(tmp, ignore_errors=True)
            except Exception:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
        ctx = WorkerEnvContext(env_key=key,
                               env_vars=dict(env.get("env_vars") or {}))
        if reqs and find_links:
            # pylibs FIRST: installed requirement versions must shadow
            # system site-packages (the version-isolation guarantee)
            ctx.pythonpath_prepend.append(os.path.join(stage, "pylibs"))
        if env.get("working_dir") is not None:
            target = os.path.join(stage, "working_dir")
            ctx.cwd = target
            ctx.pythonpath_prepend.append(target)
        for i in range(len(env.get("py_modules") or [])):
            # a module DIRECTORY is importable from its parent; a staged
            # tree of plain files is importable from the target itself
            ctx.pythonpath_prepend.append(
                os.path.join(stage, f"py_module_{i}"))
        return ctx

    @staticmethod
    def _stage_path(src: str, target: str):
        if not os.path.exists(src):
            raise RuntimeEnvError(f"runtime_env path does not exist: {src}")
        if src.endswith(".zip") and os.path.isfile(src):
            os.makedirs(target, exist_ok=True)
            with zipfile.ZipFile(src) as zf:
                zf.extractall(target)
        elif os.path.isdir(src):
            shutil.copytree(src, target)
        else:
            raise RuntimeEnvError(
                f"runtime_env path must be a directory or .zip: {src}")

    @staticmethod
    def _pip_install(env: dict, reqs: List[str], find_links: List[str],
                     target: str):
        """Offline dependency isolation (reference plugin:
        python/ray/_private/runtime_env/pip.py): install from LOCAL
        wheel/sdist directories into a per-env --target tree that the
        worker's PYTHONPATH prepends ahead of system site-packages.
        Version conflicts between envs cannot collide — each env reads
        its own tree. No venv on purpose (see runtime_env.py docstring)."""
        import subprocess
        import sys

        for fl in find_links:
            if not os.path.isdir(fl):
                raise RuntimeEnvError(
                    f"pip_find_links dir does not exist: {fl}")
        timeout = float((env.get("config") or {})
                        .get("setup_timeout_seconds", 300.0))
        cmd = [sys.executable, "-m", "pip", "install", "--no-index",
               "--disable-pip-version-check", "--quiet",
               "--target", target]
        for fl in find_links:
            cmd += ["--find-links", fl]
        cmd += list(reqs)
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout)
        except subprocess.TimeoutExpired as e:
            raise RuntimeEnvError(
                f"pip install timed out after {timeout:.0f}s") from e
        if proc.returncode != 0:
            raise RuntimeEnvError(
                "pip install failed (offline --no-index install from "
                f"{find_links}): {proc.stderr.strip()[-800:]}")

    @staticmethod
    def _check_pip(reqs: List[str]):
        """No network egress on this image: a requirement is satisfiable only
        if the distribution is already importable. Anything else must fail
        the env (reference: RuntimeEnvSetupError), never silently run without
        the dependency."""
        for req in reqs:
            name = (req.split(";")[0].split("==")[0].split(">=")[0]
                    .split("<=")[0].split("[")[0].strip())
            mod = name.replace("-", "_")
            if importlib.util.find_spec(mod) is None:
                raise RuntimeEnvError(
                    f"pip requirement {req!r} is not installed and this "
                    "environment has no package index access")
