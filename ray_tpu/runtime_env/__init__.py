"""Runtime environments: per-task/actor/job process environments.

Reference: ``python/ray/_private/runtime_env/`` (plugins + agent) — here a
spec (:class:`RuntimeEnv`), a per-raylet materializer (:class:`RuntimeEnvAgent`)
and worker-pool keying by env hash.
"""

from .runtime_env import RuntimeEnv, RuntimeEnvError, env_hash
from .agent import RuntimeEnvAgent, WorkerEnvContext

__all__ = [
    "RuntimeEnv", "RuntimeEnvError", "env_hash",
    "RuntimeEnvAgent", "WorkerEnvContext",
]
