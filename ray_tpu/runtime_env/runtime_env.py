"""RuntimeEnv spec — validated, hashable description of a worker environment.

Reference: ``python/ray/runtime_env/runtime_env.py`` (the ``RuntimeEnv``
dict-like with known fields) and the plugin field semantics from
``python/ray/_private/runtime_env/``. Supported fields:

- ``env_vars``: {str: str} merged into the worker process environment.
- ``working_dir``: local directory (or ``.zip``) copied into the session and
  used as the worker's cwd; also prepended to ``PYTHONPATH`` so task code
  can import modules shipped alongside the driver.
- ``py_modules``: list of local module directories / zips, each staged and
  prepended to ``PYTHONPATH``.
- ``pip``: list of requirement strings (reference plugin:
  ``python/ray/_private/runtime_env/pip.py``). With ``pip_find_links``
  set, requirements are REALLY installed — ``pip install --no-index
  --find-links <dirs> --target <staged pylibs>`` — and the staged tree is
  prepended to the worker's ``PYTHONPATH`` ahead of system site-packages,
  so two jobs can run CONFLICTING versions of the same package
  concurrently. Dependency isolation without a per-env virtualenv is a
  deliberate redesign: a venv swaps the interpreter and forfeits the
  forkserver warm boot; path-precedence isolation gives the same
  version-conflict guarantee while env-keyed workers exec a fresh
  interpreter anyway. Without ``pip_find_links`` (no package source — this
  image has no network egress), requirements that are already importable
  are accepted, anything else raises :class:`RuntimeEnvError` — matching
  the reference's RuntimeEnvSetupError contract.
- ``pip_find_links``: list of local directories holding wheels/sdists
  (the offline package source for ``pip``).
- ``config``: {"setup_timeout_seconds": float} (validation only).

The env hash keys worker pools (reference: worker_pool.h keyed by runtime
env hash) — two tasks share idle workers only when their materialized
environment is byte-identical.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


class RuntimeEnvError(Exception):
    """Environment could not be validated or materialized; tasks using it
    fail with this error rather than running in the wrong env."""


_KNOWN_FIELDS = {"env_vars", "working_dir", "py_modules", "pip",
                 "pip_find_links", "config"}


class RuntimeEnv(dict):
    """Dict subclass so user code can pass a plain dict anywhere."""

    def __init__(self, *, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None,
                 py_modules: Optional[List[str]] = None,
                 pip: Optional[List[str]] = None,
                 pip_find_links: Optional[List[str]] = None,
                 config: Optional[Dict[str, Any]] = None):
        super().__init__()
        if env_vars:
            self["env_vars"] = dict(env_vars)
        if working_dir is not None:
            self["working_dir"] = working_dir
        if py_modules:
            self["py_modules"] = list(py_modules)
        if pip:
            self["pip"] = list(pip)
        if pip_find_links:
            self["pip_find_links"] = list(pip_find_links)
        if config:
            self["config"] = dict(config)
        validate(self)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["RuntimeEnv"]:
        if not d:
            return None
        return cls(env_vars=d.get("env_vars"), working_dir=d.get("working_dir"),
                   py_modules=d.get("py_modules"), pip=d.get("pip"),
                   pip_find_links=d.get("pip_find_links"),
                   config=d.get("config"))


def validate(env: dict) -> None:
    unknown = set(env) - _KNOWN_FIELDS
    if unknown:
        raise RuntimeEnvError(f"unknown runtime_env fields: {sorted(unknown)}")
    ev = env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str) for k, v in ev.items()):
        raise RuntimeEnvError("env_vars must be {str: str}")
    wd = env.get("working_dir")
    if wd is not None and not isinstance(wd, str):
        raise RuntimeEnvError("working_dir must be a path string")
    for mod in env.get("py_modules") or []:
        if not isinstance(mod, str):
            raise RuntimeEnvError("py_modules entries must be path strings")
    for req in env.get("pip") or []:
        if not isinstance(req, str):
            raise RuntimeEnvError("pip entries must be requirement strings")
    for fl in env.get("pip_find_links") or []:
        if not isinstance(fl, str):
            raise RuntimeEnvError("pip_find_links entries must be path strings")
    if env.get("pip_find_links") and not env.get("pip"):
        raise RuntimeEnvError("pip_find_links requires pip requirements")


def merge(base: Optional[dict], override: Optional[dict]) -> Optional[dict]:
    """Job-default env + per-task override (reference semantics: child
    env_vars update the parent's; other fields replace wholesale)."""
    if not base:
        return dict(override) if override else None
    if not override:
        return dict(base)
    out = dict(base)
    for k, v in override.items():
        if k == "env_vars":
            ev = dict(base.get("env_vars") or {})
            ev.update(v or {})
            out["env_vars"] = ev
        else:
            out[k] = v
    return out


_HASH_TTL_S = 2.0
_hash_cache: Dict[str, tuple] = {}
_hash_lock = threading.Lock()


def env_hash(env: Optional[dict]) -> Optional[str]:
    """Stable content hash used as the worker-pool key. Local paths are
    hashed by their resolved path + mtime tree signature so an edited
    working_dir yields a fresh environment.

    Tree-walking every file is too hot for per-task submission (a 10k-task
    storm over one env must not stat the tree 10k times), so results are
    memoized for a short TTL — an edit is picked up within _HASH_TTL_S, and
    a task storm pays one walk per window."""
    if not env:
        return None
    cache_key = json.dumps(env, sort_keys=True, default=str)
    now = time.monotonic()
    with _hash_lock:
        hit = _hash_cache.get(cache_key)
        if hit is not None and now - hit[1] < _HASH_TTL_S:
            return hit[0]
    canon: Dict[str, Any] = {}
    for k in sorted(env):
        v = env[k]
        if k in ("working_dir",) and isinstance(v, str):
            canon[k] = [v, _tree_signature(v)]
        elif k in ("py_modules", "pip_find_links"):
            # a wheel dropped into a find-links dir must yield a fresh env
            canon[k] = [[m, _tree_signature(m)] for m in v]
        else:
            canon[k] = v
    blob = json.dumps(canon, sort_keys=True, default=str).encode()
    out = hashlib.sha1(blob).hexdigest()[:16]
    with _hash_lock:
        _hash_cache[cache_key] = (out, now)
        if len(_hash_cache) > 1024:
            _hash_cache.clear()
    return out


def _tree_signature(path: str) -> str:
    """Cheap change-detection: (relpath, size, mtime_ns) of every file."""
    if not os.path.exists(path):
        return "missing"
    if os.path.isfile(path):
        st = os.stat(path)
        return f"{st.st_size}:{st.st_mtime_ns}"
    items = []
    for root, _dirs, files in os.walk(path):
        for f in sorted(files):
            fp = os.path.join(root, f)
            try:
                st = os.stat(fp)
            except OSError:
                continue
            items.append(f"{os.path.relpath(fp, path)}:{st.st_size}:{st.st_mtime_ns}")
    return hashlib.sha1("|".join(items).encode()).hexdigest()[:16]
