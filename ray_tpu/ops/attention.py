"""Attention frontend: dispatch + differentiable flash attention.

``flash_attention`` takes (B, S, H, D) activations, dispatches the forward to
the Pallas TPU kernel (``ops/pallas/flash_attention.py``) on TPU backends and
to a fused XLA reference elsewhere, and installs a memory-efficient blockwise
backward via ``jax.custom_vjp`` (two ``lax.scan`` passes, materializing at
most an S×block score tile at a time — never the S×S matrix).

Net-new relative to the reference framework, which ships no attention
implementation (SURVEY.md §2.3/§5: long-context delegated to vLLM).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _use_pallas() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def mha_reference(q, k, v, causal: bool = True, scale: Optional[float] = None):
    """Naive O(S²)-memory attention, (B, S, H, D) layout. Test oracle."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    hq, hkv = q.shape[2], k.shape[2]
    if hq != hkv:
        k = jnp.repeat(k, hq // hkv, axis=2)
        v = jnp.repeat(v, hq // hkv, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        sq, skv = s.shape[-2], s.shape[-1]
        row = jnp.arange(sq)[:, None]
        col = jnp.arange(skv)[None, :]
        s = jnp.where(row >= col, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def _fwd_xla(q, k, v, causal, scale):
    """Fused full-matrix forward returning (out, lse); (B, H, S, D) layout.

    Used off-TPU (tests, CPU dry-runs) where VMEM tiling doesn't apply.
    """
    if q.shape[1] != k.shape[1]:  # GQA
        k = jnp.repeat(k, q.shape[1] // k.shape[1], axis=1)
        v = jnp.repeat(v, q.shape[1] // v.shape[1], axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        row = jnp.arange(s.shape[-2])[:, None]
        col = jnp.arange(s.shape[-1])[None, :]
        s = jnp.where(row >= col, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)  # all-masked rows
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p / jnp.maximum(l, 1e-30),
                     v.astype(jnp.float32))
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    return out.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_lse(q, k, v, causal, scale, block):
    """Joint (out, lse) primitive so downstream consumers of lse (ring
    attention merges) stay differentiable: bwd handles the dlse cotangent
    via the extra ``P·dlse`` term in dS."""
    return _flash_fwd_dispatch(q, k, v, causal, scale, block)


def _flash_fwd_dispatch(q, k, v, causal, scale, block):
    if _use_pallas():
        from ray_tpu.ops.pallas.flash_attention import flash_attention_fwd_pallas

        return flash_attention_fwd_pallas(
            q, k, v, causal=causal, scale=scale,
            block_q=block, block_kv=block)
    return _fwd_xla(q, k, v, causal, scale)


def _flash_lse_fwd(q, k, v, causal, scale, block):
    out, lse = _flash_fwd_dispatch(q, k, v, causal, scale, block)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(causal, scale, block, res, cotangents):
    """Backward dispatch: Pallas TPU kernels on TPU, blockwise XLA scan
    elsewhere. Both compute the standard recompute-form flash backward."""
    if _use_pallas():
        from ray_tpu.ops.pallas.flash_attention import flash_attention_bwd_pallas

        dout, dlse = cotangents
        q, k, v, out, lse = res
        delta = (jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                         axis=-1) - dlse.astype(jnp.float32))
        dq, dk, dv = flash_attention_bwd_pallas(
            q, k, v, lse, delta, dout, causal=causal, scale=scale,
            block_q=block, block_kv=block)
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)
    return _flash_bwd_xla(causal, scale, block, res, cotangents)


def _flash_bwd_xla(causal, scale, block, res, cotangents):
    """Blockwise flash backward, (B, H, S, D) layout.

    Standard recompute formulation: with P = exp(S·scale − lse) and
    Δ_i = Σ_d dO_id·O_id,
        dV = Pᵀ·dO,  dS = P ∘ (dO·Vᵀ − Δ + dlse),  dQ = scale·dS·K,
        dK = scale·dSᵀ·Q  (the dlse term makes the lse output differentiable).
    Pass 1 scans kv blocks accumulating dQ; pass 2 scans q blocks
    accumulating dK/dV — each step touches only an S×block tile.
    """
    dout, dlse = cotangents
    q, k, v, out, lse = res
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    group = hq // hkv
    if group > 1:
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    do = dout.astype(jnp.float32)
    delta = jnp.sum(do * out.astype(jnp.float32), axis=-1) \
        - dlse.astype(jnp.float32)                               # (B,H,Sq)

    blk = min(block, sq, skv)
    nkv = -(-skv // blk)
    nq = -(-sq // blk)
    skv_p, sq_p = nkv * blk, nq * blk
    pad_kv = [(0, 0), (0, 0), (0, skv_p - skv), (0, 0)]
    pad_q = [(0, 0), (0, 0), (0, sq_p - sq), (0, 0)]
    kp = jnp.pad(kf, pad_kv)
    vp = jnp.pad(vf, pad_kv)
    qp = jnp.pad(qf, pad_q)
    dop = jnp.pad(do, pad_q)
    lsep = jnp.pad(lse, [(0, 0), (0, 0), (0, sq_p - sq)],
                   constant_values=NEG_INF)
    deltap = jnp.pad(delta, [(0, 0), (0, 0), (0, sq_p - sq)])

    row_q = jnp.arange(sq)
    col_kv = jnp.arange(skv_p)

    def scores(qb, kb):
        return jnp.einsum("bhqd,bhkd->bhqk", qb, kb) * scale

    # Pass 1: dQ — scan over kv blocks against the full (unpadded) q.
    kvb = kp.reshape(b, hq, nkv, blk, d).transpose(2, 0, 1, 3, 4)
    vvb = vp.reshape(b, hq, nkv, blk, d).transpose(2, 0, 1, 3, 4)

    def dq_step(dq_acc, xs):
        i, kb, vb = xs
        col = i * blk + jnp.arange(blk)
        s = scores(qf, kb)                                   # (B,H,Sq,blk)
        mask = (col[None, :] < skv)
        if causal:
            mask = mask & (row_q[:, None] >= col[None, :])
        p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, vb)
        ds = p * (dp - delta[..., None])
        return dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, kb) * scale, None

    dq, _ = jax.lax.scan(
        dq_step, jnp.zeros_like(qf),
        (jnp.arange(nkv), kvb, vvb))

    # Pass 2: dK/dV — scan over q blocks against the full (padded) k/v.
    qb_ = qp.reshape(b, hq, nq, blk, d).transpose(2, 0, 1, 3, 4)
    dob_ = dop.reshape(b, hq, nq, blk, d).transpose(2, 0, 1, 3, 4)
    lseb_ = lsep.reshape(b, hq, nq, blk).transpose(2, 0, 1, 3)
    deltab_ = deltap.reshape(b, hq, nq, blk).transpose(2, 0, 1, 3)

    def dkv_step(carry, xs):
        dk_acc, dv_acc = carry
        i, qb, dob, lseb, deltab = xs
        row = i * blk + jnp.arange(blk)
        s = scores(qb, kp)                                   # (B,H,blk,Skv_p)
        mask = (row[:, None] < sq) & (col_kv[None, :] < skv)
        if causal:
            mask = mask & (row[:, None] >= col_kv[None, :])
        p = jnp.where(mask, jnp.exp(s - lseb[..., None]), 0.0)
        dv_acc = dv_acc + jnp.einsum("bhqk,bhqd->bhkd", p, dob)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dob, vp)
        ds = p * (dp - deltab[..., None])
        dk_acc = dk_acc + jnp.einsum("bhqk,bhqd->bhkd", ds, qb) * scale
        return (dk_acc, dv_acc), None

    (dkp, dvp), _ = jax.lax.scan(
        dkv_step, (jnp.zeros_like(kp), jnp.zeros_like(vp)),
        (jnp.arange(nq), qb_, dob_, lseb_, deltab_))
    dk = dkp[:, :, :skv]
    dv = dvp[:, :, :skv]

    if group > 1:
        dk = dk.reshape(b, hkv, group, skv, d).sum(axis=2)
        dv = dv.reshape(b, hkv, group, skv, d).sum(axis=2)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None, block: int = 512):
    """Differentiable flash attention, (B, S, H, D) layout (GQA-aware)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out, _ = _flash_lse(qt, kt, vt, causal, scale, block)
    return out.transpose(0, 2, 1, 3)


def flash_attention_with_lse(q, k, v, causal: bool = True,
                             scale: Optional[float] = None, block: int = 512):
    """Differentiable variant returning (out, lse) in (B, S, H, D) /
    (B, H, S) layouts; building block for ring attention."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _flash_lse(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal, scale, block)
    return out.transpose(0, 2, 1, 3), lse
