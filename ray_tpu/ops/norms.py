"""Normalization ops.

Plain jnp implementations: XLA fuses these into neighboring ops on TPU, so a
Pallas kernel buys nothing here (the win is in attention, where the naive
algorithm materializes the S×S score matrix in HBM).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rmsnorm(x, weight, eps: float = 1e-6):
    """RMSNorm in f32 accumulation regardless of input dtype."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * (1.0 + weight.astype(jnp.float32))).astype(dtype)


def layernorm(x, weight, bias, eps: float = 1e-5):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    y = (x - mu) * (var + eps) ** -0.5
    return (y * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
