"""Pallas TPU kernels (HBM→VMEM tiled, MXU-shaped)."""
