"""Paged flash-decoding attention (Pallas TPU): one new query token per
slot against that slot's KV cache stored in non-contiguous fixed-size
blocks (a vLLM-style paged KV pool, TPU-native).

Capability bar: vLLM's paged attention, which the reference delegates to
(``python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py``).
The TPU shape of the idea: the pool is one static (num_blocks, bs, KV, D)
array; each slot's logical cache is the sequence of pool blocks named by
its block-table row. Block tables ride as SCALAR-PREFETCH operands, so
the kernel's BlockSpec index maps translate (slot, logical block) →
physical pool block at grid-issue time — the gather never materializes a
contiguous per-slot cache in HBM.

GQA is an unrolled static loop over kv heads inside each program (same
rationale as ``decode_attention.py``: the KV axis is too small/unaligned
to be a grid dimension, and looping in-program reads each cache block
exactly once).

Layout contract:
    q        (B, 1, H, D)    new-token queries
    k_pool   (NB, bs, KV, D) paged key pool (one layer)
    v_pool   (NB, bs, KV, D)
    tables   (B, MBS) int32  physical block id per logical block; entries
                             past the valid prefix MUST name a real block
                             (conventionally the reserved null block 0) —
                             they are masked out, but are still prefetched
    lengths  (B,) int32      valid tokens per slot (incl. the new token)

Online-softmax recurrence identical to ``decode_attention.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.pallas._compat import CompilerParams as _CompilerParams

NEG_INF = -1e30
_LANES = 128


def _paged_kernel(tables_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref,
                  *, scale: float, block_s: int, num_blocks: int,
                  num_kv: int, group: int):
    b = pl.program_id(0)
    ib = pl.program_id(1)

    @pl.when(ib == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    length = len_ref[b]

    @pl.when(ib * block_s < length)
    def _compute():
        for j in range(num_kv):          # static unroll over kv heads
            lo, hi = j * group, (j + 1) * group
            q = q_ref[0, lo:hi, :]       # (group, D)
            k = k_ref[0, :, j, :]        # (bs, D)
            v = v_ref[0, :, j, :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # (group, bs)
            col = ib * block_s + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(col < length, s, NEG_INF)

            m_prev = m_ref[lo:hi, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[lo:hi, :] = jnp.broadcast_to(
                l_ref[lo:hi, :1] * alpha + jnp.sum(p, axis=1,
                                                   keepdims=True),
                (group, _LANES))
            acc_ref[lo:hi, :] = acc_ref[lo:hi, :] * alpha + \
                jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            m_ref[lo:hi, :] = jnp.broadcast_to(m_new, (group, _LANES))

    @pl.when(ib == num_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, tables, lengths, *,
                           scale: float, interpret: bool = False):
    """q (B,1,H,D); k/v_pool (NB,bs,KV,D); tables (B,MBS) int32;
    lengths (B,) int32. Returns (B, 1, H, D) in q.dtype."""
    B, _, H, D = q.shape
    bs, KV = k_pool.shape[1], k_pool.shape[2]
    MBS = tables.shape[1]
    if H % KV:
        raise ValueError(f"q heads {H} not a multiple of kv heads {KV}")
    group = H // KV

    qh = q.reshape(B, H, D)

    kernel = functools.partial(
        _paged_kernel, scale=scale, block_s=bs, num_blocks=MBS,
        num_kv=KV, group=group)

    def kv_ix(b, ib, tables_ref, len_ref):
        del len_ref
        return (tables_ref[b, ib], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, MBS),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, ib, *_: (b, 0, 0)),
            pl.BlockSpec((1, bs, KV, D), kv_ix),
            pl.BlockSpec((1, bs, KV, D), kv_ix),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, ib, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),
            pltpu.VMEM((H, _LANES), jnp.float32),
            pltpu.VMEM((H, _LANES), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32),
      qh, k_pool, v_pool)

    return out.reshape(B, 1, H, D)


def paged_attention_reference(q, k_pool, v_pool, tables, lengths, *,
                              scale: float):
    """XLA path (and the kernel's correctness oracle): gather the per-slot
    cache via the block table, then grouped-einsum attention. Used on CPU
    and as the non-Pallas fallback in ``models.paged_cache``."""
    B, _, H, D = q.shape
    bs, KV = k_pool.shape[1], k_pool.shape[2]
    MBS = tables.shape[1]
    group = H // KV
    S = MBS * bs
    k = k_pool[tables].reshape(B, S, KV, D)      # (B, MBS, bs, KV, D) →
    v = v_pool[tables].reshape(B, S, KV, D)
    qg = q.astype(jnp.float32).reshape(B, KV, group, D)
    s = jnp.einsum("bkgd,bskd->bkgs", qg, k.astype(jnp.float32)) * scale
    mask = jnp.arange(S)[None, :] < lengths[:, None]
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", p, v.astype(jnp.float32))
    return out.reshape(B, 1, H, D).astype(q.dtype)
