"""Flash-decoding attention kernel (Pallas TPU): one new query token per
slot against that slot's KV cache.

The serving engine's decode step is HBM-bandwidth-bound: every step
streams the whole KV cache once. This kernel keeps the running softmax
state in VMEM while the cache streams through in blocks (online softmax,
same recurrence as the training kernel in ``flash_attention.py``) and
handles GQA by loading one kv head's whole query GROUP as the left matmul
operand — no head-repeated cache materialization, which the previous XLA
path paid group× per step.

Layout contract: q (B, 1, H, D); k/v cache (B, S, KV, D); lengths (B,)
int32 (valid prefix incl. the new token). Grid = (B·KV, S blocks) with the
S dimension sequential; per-slot length masking uses a (1,1) VMEM block of
the lengths array.

Net-new vs the reference (its serving attention lives in vLLM's paged
kernels, outside the repo); this is the TPU analog of flash-decoding.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref,
                   *, scale: float, block_s: int, num_s_blocks: int,
                   kv_len: int):
    ik = pl.program_id(1)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    length = len_ref[0, 0]
    # blocks wholly past the valid prefix contribute nothing
    @pl.when(ik * block_s < length)
    def _compute():
        q = q_ref[0]                       # (group, D)
        k = k_ref[0, :, 0, :]              # (Bs, D)
        v = v_ref[0, :, 0, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (group, Bs)
        col = ik * block_s + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < length, s, NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            l_ref[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True),
            l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(ik == num_s_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, lengths, *, scale: float,
                     block_s: int = 512, interpret: bool = False):
    """q: (B, 1, H, D); k/v_cache: (B, S, KV, D); lengths: (B,) int32.
    Returns (B, 1, H, D) in q.dtype."""
    B, _, H, D = q.shape
    S, KV = k_cache.shape[1], k_cache.shape[2]
    if H % KV:
        raise ValueError(f"q heads {H} not a multiple of kv heads {KV}")
    group = H // KV

    block_s = max(16, min(block_s, S))
    s_p = math.ceil(S / block_s) * block_s
    if s_p != S:
        pad = ((0, 0), (0, s_p - S), (0, 0), (0, 0))
        k_cache = jnp.pad(k_cache, pad)
        v_cache = jnp.pad(v_cache, pad)
    ns = s_p // block_s

    qg = q.reshape(B, KV, group, D).reshape(B * KV, group, D)
    # one (1,1) scalar block of lengths per (b, kv) program
    len_in = jnp.broadcast_to(lengths[:, None], (B, KV)) \
        .reshape(B * KV, 1).astype(jnp.int32)

    kernel = functools.partial(
        _decode_kernel, scale=scale, block_s=block_s, num_s_blocks=ns,
        kv_len=S)

    def kv_ix(bk, ik):
        return (bk // KV, ik, bk % KV, 0)

    out = pl.pallas_call(
        kernel,
        grid=(B * KV, ns),
        in_specs=[
            pl.BlockSpec((1, 1), lambda bk, ik: (bk, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, group, D), lambda bk, ik: (bk, 0, 0)),
            pl.BlockSpec((1, block_s, 1, D), kv_ix),
            pl.BlockSpec((1, block_s, 1, D), kv_ix),
        ],
        out_specs=pl.BlockSpec((1, group, D), lambda bk, ik: (bk, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B * KV, group, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((group, D), jnp.float32),
            pltpu.VMEM((group, _LANES), jnp.float32),
            pltpu.VMEM((group, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(len_in, qg, k_cache, v_cache)

    return out.reshape(B, KV, group, D).reshape(B, 1, H, D)
