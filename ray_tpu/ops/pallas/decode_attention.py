"""Flash-decoding attention kernel (Pallas TPU): one new query token per
slot against that slot's KV cache.

The serving engine's decode step is HBM-bandwidth-bound: every step
streams the whole KV cache once. This kernel keeps the running softmax
state in VMEM while the cache streams through in blocks (online softmax,
same recurrence as the training kernel in ``flash_attention.py``) and
handles GQA by an unrolled static loop over kv heads INSIDE the program:
each (slot, seq-block) grid step copies its cache block once and every
kv head consumes its slice — no head-repeated cache materialization and
no per-kv-head re-streaming. (The kv-head axis cannot be a grid
dimension with a (…, 1, D) block: Mosaic requires the last two block
dims be tile-aligned or span the array, and KV is small and unaligned.)

Layout contract: q (B, 1, H, D); k/v cache (B, S, KV, D); lengths (B,)
int32 (valid prefix incl. the new token). Grid = (B, S blocks) with the
S dimension sequential; lengths ride as a scalar-prefetch operand (the
whole array in SMEM, indexed by program id — a per-program (1,1) SMEM
block would violate Mosaic's last-two-dims tiling rule).

Net-new vs the reference (its serving attention lives in vLLM's paged
kernels, outside the repo); this is the TPU analog of flash-decoding.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.pallas._compat import CompilerParams as _CompilerParams

NEG_INF = -1e30
_LANES = 128


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref,
                   *, scale: float, block_s: int, num_s_blocks: int,
                   num_kv: int, group: int):
    ik = pl.program_id(1)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    length = len_ref[pl.program_id(0)]
    # blocks wholly past the valid prefix contribute nothing
    @pl.when(ik * block_s < length)
    def _compute():
        for j in range(num_kv):          # static unroll over kv heads
            lo, hi = j * group, (j + 1) * group
            q = q_ref[0, lo:hi, :]       # (group, D)
            k = k_ref[0, :, j, :]        # (Bs, D)
            v = v_ref[0, :, j, :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # (group, Bs)
            col = ik * block_s + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(col < length, s, NEG_INF)

            m_prev = m_ref[lo:hi, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[lo:hi, :] = jnp.broadcast_to(
                l_ref[lo:hi, :1] * alpha + jnp.sum(p, axis=1,
                                                   keepdims=True),
                (group, _LANES))
            acc_ref[lo:hi, :] = acc_ref[lo:hi, :] * alpha + \
                jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            m_ref[lo:hi, :] = jnp.broadcast_to(m_new, (group, _LANES))

    @pl.when(ik == num_s_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, lengths, *, scale: float,
                     block_s: int = 512, interpret: bool = False):
    """q: (B, 1, H, D); k/v_cache: (B, S, KV, D); lengths: (B,) int32.
    Returns (B, 1, H, D) in q.dtype."""
    B, _, H, D = q.shape
    S, KV = k_cache.shape[1], k_cache.shape[2]
    if H % KV:
        raise ValueError(f"q heads {H} not a multiple of kv heads {KV}")
    group = H // KV

    block_s = max(16, min(block_s, S))
    s_p = math.ceil(S / block_s) * block_s
    if s_p != S:
        pad = ((0, 0), (0, s_p - S), (0, 0), (0, 0))
        k_cache = jnp.pad(k_cache, pad)
        v_cache = jnp.pad(v_cache, pad)
    ns = s_p // block_s

    # queries laid out (B, H, D) with kv-head groups contiguous in H
    qh = q.reshape(B, H, D)

    kernel = functools.partial(
        _decode_kernel, scale=scale, block_s=block_s, num_s_blocks=ns,
        num_kv=KV, group=group)

    # lengths ride as a scalar-prefetch operand (whole array in SMEM,
    # indexed by program id) — a (1,1) SMEM block would violate the
    # last-two-dims tiling rule
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, ns),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, ik, *_: (b, 0, 0)),
            pl.BlockSpec((1, block_s, KV, D),
                         lambda b, ik, *_: (b, ik, 0, 0)),
            pl.BlockSpec((1, block_s, KV, D),
                         lambda b, ik, *_: (b, ik, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, ik, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),
            pltpu.VMEM((H, _LANES), jnp.float32),
            pltpu.VMEM((H, _LANES), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qh, k_cache, v_cache)

    return out.reshape(B, 1, H, D)
