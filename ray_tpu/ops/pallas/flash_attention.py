"""Flash-attention forward kernel (Pallas TPU).

Online-softmax tiling: grid = (batch·heads, q blocks, kv blocks) with the kv
dimension innermost ("arbitrary" = sequential), carrying the running max /
normalizer / accumulator in VMEM scratch so the S×S score matrix never touches
HBM. Causal blocks strictly above the diagonal are skipped with ``pl.when``
(compute is elided; the scratch state is carried through unchanged).

Layout contract: inputs are (B, H, S, D); GQA kv heads are resolved in the kv
BlockSpec index map (no materialized head repeat). Matmuls run on the MXU in
the input dtype with f32 accumulation (``preferred_element_type``).

The reference framework has no kernel layer (its attention lives in torch /
vLLM, outside the repo); this file is net-new TPU-first work (SURVEY.md §5
"Long-context": TPU-native plan).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.attention import NEG_INF

_LANES = 128


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, block_q: int, block_kv: int,
                kv_len: int, num_kv_blocks: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: q rows [iq·Bq, iq·Bq+Bq) never see kv cols >= (iq+1)·Bq, so
    # blocks strictly above the diagonal are skipped entirely.
    should_run = (ik * block_kv < (iq + 1) * block_q) if causal else True

    @pl.when(should_run)
    def _compute():
        q = q_ref[0]                      # (Bq, D)
        k = k_ref[0]                      # (Bkv, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (Bq, Bkv) f32

        col = ik * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < kv_len               # padded kv tail
        if causal:
            row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, row >= col)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]                               # (Bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                              # (Bq, Bkv)
        alpha = jnp.exp(m_prev - m_new)                     # (Bq, 1)
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == num_kv_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        # Fully-masked rows (padding) would divide by zero; keep them finite.
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l == 0.0, NEG_INF, m_ref[:, :1] + jnp.log(l_safe))
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def flash_attention_fwd_pallas(q, k, v, *, causal: bool, scale: float,
                               block_q: int = 512, block_kv: int = 512,
                               interpret: bool = False):
    """q: (B, Hq, Sq, D); k, v: (B, Hkv, Skv, D).

    Returns ``(out, lse)``: out (B, Hq, Sq, D) in q.dtype, lse (B, Hq, Sq)
    f32 where ``lse[i] = log(sum_j exp(scale·q_i·k_j))`` over unmasked j.
    """
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv

    block_q = max(16, min(block_q, sq))
    block_kv = max(16, min(block_kv, skv))
    sq_p = math.ceil(sq / block_q) * block_q
    skv_p = math.ceil(skv / block_kv) * block_kv
    if sq_p != sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if skv_p != skv:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
    nq = sq_p // block_q
    nk = skv_p // block_kv

    def q_index(bh, iq, ik):
        return (bh, iq, 0)

    def kv_index(bh, iq, ik):
        return (bh // hq * hkv + (bh % hq) // group, ik, 0)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_kv=block_kv, kv_len=skv, num_kv_blocks=nk)

    out, lse = pl.pallas_call(
        kernel,
        grid=(b * hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_kv, d), kv_index),
            pl.BlockSpec((1, block_kv, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, iq, ik: (bh, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hq, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b * hq, sq_p, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q.reshape(b * hq, sq_p, d),
      k.reshape(b * hkv, skv_p, d),
      v.reshape(b * hkv, skv_p, d))

    out = out.reshape(b, hq, sq_p, d)[:, :, :sq]
    lse = lse[:, :, 0].reshape(b, hq, sq_p)[:, :, :sq]
    return out, lse
