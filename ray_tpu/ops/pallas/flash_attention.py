"""Flash-attention forward kernel (Pallas TPU).

Online-softmax tiling: grid = (batch·heads, q blocks, kv blocks) with the kv
dimension innermost ("arbitrary" = sequential), carrying the running max /
normalizer / accumulator in VMEM scratch so the S×S score matrix never touches
HBM. Causal blocks strictly above the diagonal are skipped with ``pl.when``
(compute is elided; the scratch state is carried through unchanged).

Layout contract: inputs are (B, H, S, D); GQA kv heads are resolved in the kv
BlockSpec index map (no materialized head repeat). Matmuls run on the MXU in
the input dtype with f32 accumulation (``preferred_element_type``).

The reference framework has no kernel layer (its attention lives in torch /
vLLM, outside the repo); this file is net-new TPU-first work (SURVEY.md §5
"Long-context": TPU-native plan).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.pallas._compat import CompilerParams as _CompilerParams

from ray_tpu.ops.attention import NEG_INF

_LANES = 128


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, block_q: int, block_kv: int,
                kv_len: int, num_kv_blocks: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: q rows [iq·Bq, iq·Bq+Bq) never see kv cols >= (iq+1)·Bq, so
    # blocks strictly above the diagonal are skipped entirely.
    should_run = (ik * block_kv < (iq + 1) * block_q) if causal else True

    @pl.when(should_run)
    def _compute():
        q = q_ref[0]                      # (Bq, D)
        k = k_ref[0]                      # (Bkv, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (Bq, Bkv) f32

        col = ik * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < kv_len               # padded kv tail
        if causal:
            row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, row >= col)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]                               # (Bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                              # (Bq, Bkv)
        alpha = jnp.exp(m_prev - m_new)                     # (Bq, 1)
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == num_kv_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        # Fully-masked rows (padding) would divide by zero; keep them finite.
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l == 0.0, NEG_INF, m_ref[:, :1] + jnp.log(l_safe))
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def flash_attention_fwd_pallas(q, k, v, *, causal: bool, scale: float,
                               block_q: int = 512, block_kv: int = 512,
                               interpret: bool = False):
    """q: (B, Hq, Sq, D); k, v: (B, Hkv, Skv, D).

    Returns ``(out, lse)``: out (B, Hq, Sq, D) in q.dtype, lse (B, Hq, Sq)
    f32 where ``lse[i] = log(sum_j exp(scale·q_i·k_j))`` over unmasked j.
    """
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv

    block_q = max(16, min(block_q, sq))
    block_kv = max(16, min(block_kv, skv))
    sq_p = math.ceil(sq / block_q) * block_q
    skv_p = math.ceil(skv / block_kv) * block_kv
    if sq_p != sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if skv_p != skv:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
    nq = sq_p // block_q
    nk = skv_p // block_kv

    def q_index(bh, iq, ik):
        return (bh, iq, 0)

    def kv_index(bh, iq, ik):
        return (bh // hq * hkv + (bh % hq) // group, ik, 0)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_kv=block_kv, kv_len=skv, num_kv_blocks=nk)

    out, lse = pl.pallas_call(
        kernel,
        grid=(b * hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_kv, d), kv_index),
            pl.BlockSpec((1, block_kv, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, iq, ik: (bh, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hq, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b * hq, sq_p, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q.reshape(b * hq, sq_p, d),
      k.reshape(b * hkv, skv_p, d),
      v.reshape(b * hkv, skv_p, d))

    out = out.reshape(b, hq, sq_p, d)[:, :, :sq]
    lse = lse[:, :, 0].reshape(b, hq, sq_p)[:, :, :sq]
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels.
#
# Both kernels keep the score matrix *transposed* relative to the forward:
# s_t = K·Qᵀ of shape (block_kv, block_q). With q as the lane (minor)
# dimension, the per-q-row vectors lse and delta — stored as (1, block_q)
# tiles — broadcast against s_t without any in-kernel transpose; every
# contraction is a plain MXU dot_general.
#
# Standard recompute formulation (P recomputed from q, k, lse):
#   P   = exp(S·scale − lse)
#   dV  = Pᵀ·dO
#   dS  = P ∘ (dO·Vᵀ − Δ)   with Δ = Σ_d dO·O − dlse (precomputed, f32)
#   dQ  = scale·dS·K          dK = scale·dSᵀ·Q
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc_ref,
                   *, scale: float, causal: bool, block_q: int,
                   block_kv: int, q_len: int, kv_len: int,
                   num_kv_blocks: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    should_run = (ik * block_kv < (iq + 1) * block_q) if causal else True

    @pl.when(should_run)
    def _compute():
        q = q_ref[0]                       # (Bq, D)
        k = k_ref[0]                       # (Bkv, D)
        v = v_ref[0]
        do = do_ref[0]                     # (Bq, D)
        lse = lse_ref[0]                   # (1, Bq) f32
        delta = delta_ref[0]               # (1, Bq) f32

        s_t = jax.lax.dot_general(         # (Bkv, Bq) = K·Qᵀ
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        qpos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s_t.shape, 1)
        kpos = ik * block_kv + jax.lax.broadcasted_iota(jnp.int32, s_t.shape, 0)
        mask = jnp.logical_and(qpos < q_len, kpos < kv_len)
        if causal:
            mask = jnp.logical_and(mask, qpos >= kpos)
        p_t = jnp.where(mask, jnp.exp(s_t - lse), 0.0)        # (Bkv, Bq)
        dp_t = jax.lax.dot_general(        # (Bkv, Bq) = V·dOᵀ
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds_t = p_t * (dp_t - delta)
        dq_acc_ref[:] += jax.lax.dot_general(   # (Bq, D) = dSᵀ_t·K·scale
            ds_t, k.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(ik == num_kv_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                    *, scale: float, causal: bool, block_q: int,
                    block_kv: int, q_len: int, kv_len: int,
                    num_q_blocks: int, num_inner: int):
    ik = pl.program_id(1)
    e = pl.program_id(2)                   # enumerates (gqa group, q block)
    iq = e % num_q_blocks

    @pl.when(e == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    # Causal: the q block must reach at least the first kv row of this block.
    should_run = ((iq + 1) * block_q > ik * block_kv) if causal else True

    @pl.when(should_run)
    def _compute():
        q = q_ref[0]                       # (Bq, D)
        k = k_ref[0]                       # (Bkv, D)
        v = v_ref[0]
        do = do_ref[0]                     # (Bq, D)
        lse = lse_ref[0]                   # (1, Bq)
        delta = delta_ref[0]

        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # (Bkv, Bq)
        qpos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s_t.shape, 1)
        kpos = ik * block_kv + jax.lax.broadcasted_iota(jnp.int32, s_t.shape, 0)
        mask = jnp.logical_and(qpos < q_len, kpos < kv_len)
        if causal:
            mask = jnp.logical_and(mask, qpos >= kpos)
        p_t = jnp.where(mask, jnp.exp(s_t - lse), 0.0)
        dv_acc_ref[:] += jax.lax.dot_general(   # (Bkv, D) = P_t·dO
            p_t, do.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds_t = p_t * (dp_t - delta)
        dk_acc_ref[:] += jax.lax.dot_general(   # (Bkv, D) = dS_t·Q·scale
            ds_t, q.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(e == num_inner - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def flash_attention_bwd_pallas(q, k, v, lse, delta, dout, *,
                               causal: bool, scale: float,
                               block_q: int = 512, block_kv: int = 512,
                               interpret: bool = False):
    """Backward pass. q/dout: (B, Hq, Sq, D); k, v: (B, Hkv, Skv, D);
    lse, delta: (B, Hq, Sq) f32 with delta = Σ_d dO·O − dlse.

    Returns (dq, dk, dv) in the input dtypes/shapes. GQA kv gradients are
    accumulated *inside* the dkv kernel (the innermost grid axis enumerates
    group × q-blocks against a resident kv tile) — no materialized
    head-repeat or post-hoc group reduction.
    """
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    group = hq // hkv

    block_q = max(16, min(block_q, sq))
    block_kv = max(16, min(block_kv, skv))
    sq_p = math.ceil(sq / block_q) * block_q
    skv_p = math.ceil(skv / block_kv) * block_kv
    if sq_p != sq:
        pad = ((0, 0), (0, 0), (0, sq_p - sq), (0, 0))
        q = jnp.pad(q, pad)
        dout = jnp.pad(dout, pad)
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, sq_p - sq)))
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, sq_p - sq)))
    if skv_p != skv:
        pad = ((0, 0), (0, 0), (0, skv_p - skv), (0, 0))
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    nq = sq_p // block_q
    nk = skv_p // block_kv

    qf = q.reshape(b * hq, sq_p, d)
    doutf = dout.reshape(b * hq, sq_p, d)
    kf = k.reshape(b * hkv, skv_p, d)
    vf = v.reshape(b * hkv, skv_p, d)
    lsef = lse.reshape(b * hq, 1, sq_p).astype(jnp.float32)
    deltaf = delta.reshape(b * hq, 1, sq_p).astype(jnp.float32)

    def q_ix(bh, iq, ik):
        return (bh, iq, 0)

    def kv_ix(bh, iq, ik):
        return (bh // hq * hkv + (bh % hq) // group, ik, 0)

    def vec_ix(bh, iq, ik):
        return (bh, 0, iq)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q,
        block_kv=block_kv, q_len=sq, kv_len=skv, num_kv_blocks=nk)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_ix),
            pl.BlockSpec((1, block_kv, d), kv_ix),
            pl.BlockSpec((1, block_kv, d), kv_ix),
            pl.BlockSpec((1, block_q, d), q_ix),
            pl.BlockSpec((1, 1, block_q), vec_ix),
            pl.BlockSpec((1, 1, block_q), vec_ix),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), q_ix),
        out_shape=jax.ShapeDtypeStruct((b * hq, sq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, doutf, lsef, deltaf)

    # dkv: grid minor axis sweeps (group, q block) pairs while one kv tile
    # and its dk/dv accumulators stay resident in VMEM.
    num_inner = group * nq

    def q_ix2(bh, ik, e):
        return (bh // hkv * hq + (bh % hkv) * group + e // nq, e % nq, 0)

    def kv_ix2(bh, ik, e):
        return (bh, ik, 0)

    def vec_ix2(bh, ik, e):
        return (bh // hkv * hq + (bh % hkv) * group + e // nq, 0, e % nq)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
        block_kv=block_kv, q_len=sq, kv_len=skv, num_q_blocks=nq,
        num_inner=num_inner)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * hkv, nk, num_inner),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_ix2),
            pl.BlockSpec((1, block_kv, d), kv_ix2),
            pl.BlockSpec((1, block_kv, d), kv_ix2),
            pl.BlockSpec((1, block_q, d), q_ix2),
            pl.BlockSpec((1, 1, block_q), vec_ix2),
            pl.BlockSpec((1, 1, block_q), vec_ix2),
        ],
        out_specs=[
            pl.BlockSpec((1, block_kv, d), kv_ix2),
            pl.BlockSpec((1, block_kv, d), kv_ix2),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hkv, skv_p, d), k.dtype),
            jax.ShapeDtypeStruct((b * hkv, skv_p, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, doutf, lsef, deltaf)

    dq = dq.reshape(b, hq, sq_p, d)[:, :, :sq]
    dk = dk.reshape(b, hkv, skv_p, d)[:, :, :skv]
    dv = dv.reshape(b, hkv, skv_p, d)[:, :, :skv]
    return dq, dk, dv
