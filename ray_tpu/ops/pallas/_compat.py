"""Pallas API compat for the jax versions this repo runs on."""

from jax.experimental.pallas import tpu as pltpu

# renamed TPUCompilerParams -> CompilerParams across jax releases
CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
