"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

Each device holds a contiguous sequence chunk of q/k/v. kv chunks rotate
around the ring via ``lax.ppermute`` (nearest-neighbor ICI hop); each step
runs the local flash kernel against the visiting chunk and folds the partial
result in with a numerically-stable log-sum-exp merge. Causality is enforced
at chunk granularity (visiting chunk strictly-past → full attend, self →
causal, future → skip) so each device does only the work its rows need.

Differentiability comes for free: the merge is plain jnp and the local kernel
is the joint (out, lse) custom-vjp primitive from ``ops.attention``.

Net-new vs the reference framework — SURVEY.md §2.3 records that ring/Ulysses
/context parallelism is absent there. Also provides ``ulysses_attention``
(all-to-all seq↔heads exchange) as the lower-latency alternative when
heads % sp == 0.

Known wall-clock headroom (future rounds): striped/zigzag chunk orderings to
balance the causal triangle across ring steps.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import NEG_INF, flash_attention_with_lse


def _merge(o1, lse1, o2, lse2):
    """Combine two normalized partial attentions (o_i, lse_i) → (o, lse)."""
    m = jnp.maximum(lse1, lse2)
    m = jnp.maximum(m, NEG_INF)  # both empty → stay finite
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    l = w1 + w2
    l_safe = jnp.where(l == 0.0, 1.0, l)
    # (B, H, S) stats vs (B, S, H, D) outputs: move heads axis.
    w1o = jnp.transpose(w1 / l_safe, (0, 2, 1))[..., None]
    w2o = jnp.transpose(w2 / l_safe, (0, 2, 1))[..., None]
    o = o1 * w1o + o2 * w2o
    return o, m + jnp.log(l_safe)


def ring_attention_local(q, k, v, axis_name: str = "sp",
                         causal: bool = True,
                         scale: Optional[float] = None, block: int = 512):
    """Per-device body; call inside shard_map with q/k/v seq-sharded on
    ``axis_name``. (B, S_local, H, D) layout."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]

    o32 = None
    lse = None
    for step in range(n):
        if step > 0:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
        if step == 0:
            o_s, lse_s = flash_attention_with_lse(
                q, k, v, causal=causal, scale=scale, block=block)
            o32, lse = o_s.astype(jnp.float32), lse_s
            continue
        src = (my - step) % n  # origin of the visiting kv chunk

        def attend(q, k, v):
            o_s, lse_s = flash_attention_with_lse(
                q, k, v, causal=False, scale=scale, block=block)
            return o_s.astype(jnp.float32), lse_s

        def skip(q, k, v):
            return (jnp.zeros(q.shape, jnp.float32),
                    jnp.full((q.shape[0], q.shape[2], q.shape[1]),
                             NEG_INF, jnp.float32))

        if causal:
            o_s, lse_s = jax.lax.cond(src < my, attend, skip, q, k, v)
        else:
            o_s, lse_s = attend(q, k, v)
        o32, lse = _merge(o32, lse, o_s, lse_s)
    return o32.astype(q.dtype)


def ulysses_attention_local(q, k, v, axis_name: str = "sp",
                            causal: bool = True,
                            scale: Optional[float] = None, block: int = 512):
    """All-to-all SP: exchange seq↔heads so each device sees the full
    sequence for H/sp heads, run dense-local flash, exchange back.
    Requires heads (incl. kv heads) divisible by the axis size."""

    def seq_to_heads(x):
        # (B, S/n, H, D) → (B, S, H/n, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    from ray_tpu.ops.attention import flash_attention

    o = flash_attention(qg, kg, vg, causal=causal, scale=scale, block=block)
    return heads_to_seq(o)


def ring_attention(q, k, v, mesh, causal: bool = True,
                   scale: Optional[float] = None,
                   sp_axis: str = "sp", heads_axis: Optional[str] = "tp",
                   batch_axes: Union[str, Sequence[str]] = ("dp", "fsdp"),
                   block: int = 512, mode: str = "ring"):
    """shard_map wrapper usable inside a jitted GSPMD program.

    q/k/v: (B, S, H, D) global arrays; resharded to
    P(batch_axes, sp_axis, heads_axis, None) per device.
    ``mode``: "ring" (ppermute) or "ulysses" (all-to-all).
    """
    from jax.sharding import PartitionSpec as P

    import inspect

    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5: experimental home
        from jax.experimental.shard_map import shard_map
    # the replication-check kwarg was renamed check_rep -> check_vma
    # independently of the module move; pick by signature, not version
    params = inspect.signature(shard_map).parameters
    smap_kw = {"check_vma": False} if "check_vma" in params \
        else {"check_rep": False}

    spec = P(batch_axes, sp_axis, heads_axis, None)
    local = (ring_attention_local if mode == "ring"
             else ulysses_attention_local)

    def body(q, k, v):
        return local(q, k, v, axis_name=sp_axis, causal=causal, scale=scale,
                     block=block)

    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, **smap_kw)(q, k, v)
