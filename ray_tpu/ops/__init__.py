"""ray_tpu.ops — TPU compute kernels.

The hot ops of the framework's model zoo: flash attention (Pallas TPU kernel
with an XLA blockwise fallback), ring attention for sequence parallelism
(collective-permute over the ``sp`` mesh axis), RMSNorm, and rotary
embeddings. The reference framework has no kernel layer at all — its compute
is delegated to torch/vLLM (SURVEY.md §2.3); here kernels are in-framework.
"""

from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.norms import layernorm, rmsnorm
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.rope import apply_rope, rope_frequencies

__all__ = [
    "apply_rope",
    "flash_attention",
    "layernorm",
    "mha_reference",
    "ring_attention",
    "rmsnorm",
    "rope_frequencies",
]
