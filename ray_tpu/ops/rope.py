"""Rotary position embeddings (non-interleaved / llama "neox" layout)."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 500000.0,
                     dtype=jnp.float32):
    """(max_seq, head_dim/2) cos/sin tables."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x, cos, sin, positions=None):
    """x: (..., seq, heads, head_dim). cos/sin: (max_seq, head_dim/2).

    ``positions``: optional (..., seq) int array for non-contiguous positions
    (decode steps, packed sequences).
    """
    if positions is None:
        seq = x.shape[-3]
        c, s = cos[:seq], sin[:seq]                # (seq, hd/2)
        c = c[:, None, :]
        s = s[:, None, :]
    else:
        c = cos[positions][..., :, None, :]
        s = sin[positions][..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
