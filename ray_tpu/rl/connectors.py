"""Connector pipelines — composable observation/batch transforms.

Reference: ``rllib/connectors/connector_v2.py`` (ConnectorV2 pieces wired
into env runners and learners) — the idea: preprocessing lives in small,
stateful, checkpointable pieces owned by the pipeline, not hard-coded into
the env runner or the model.

Env-to-module connectors transform a raw observation before the policy
sees it (normalization, frame stacking); their state ships with weights
broadcasts so rollout and learner sides stay consistent.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Connector:
    """One transform. Stateful connectors override get/set_state."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass

    def reset(self) -> None:
        """Called at episode boundaries (frame stacks flush, etc.)."""


class ConnectorPipeline(Connector):
    def __init__(self, connectors: Optional[List[Connector]] = None):
        self.connectors = list(connectors or [])

    def __call__(self, obs):
        for c in self.connectors:
            obs = c(obs)
        return obs

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.insert(0, connector)
        return self

    def insert_before(self, cls: type, connector: Connector):
        for i, c in enumerate(self.connectors):
            if isinstance(c, cls):
                self.connectors.insert(i, connector)
                return self
        raise ValueError(f"no connector of type {cls.__name__}")

    def reset(self):
        for c in self.connectors:
            c.reset()

    def get_state(self):
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state):
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])

    def output_size(self, obs_size: int) -> int:
        for c in self.connectors:
            obs_size = c.transformed_size(obs_size) \
                if hasattr(c, "transformed_size") else obs_size
        return obs_size


class Lambda(Connector):
    """Stateless functional transform."""

    def __init__(self, fn: Callable[[np.ndarray], np.ndarray]):
        self._fn = fn

    def __call__(self, obs):
        return self._fn(obs)


class ObsNormalizer(Connector):
    """Running mean/std normalization (Welford). The running stats are
    part of the connector state: the algorithm broadcasts them with the
    weights so every env runner normalizes identically."""

    def __init__(self, eps: float = 1e-8, clip: float = 10.0,
                 update: bool = True):
        self.eps = eps
        self.clip = clip
        self.update = update
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs):
        obs = np.asarray(obs, np.float64)
        if self._mean is None:
            self._mean = np.zeros_like(obs)
            self._m2 = np.ones_like(obs)
        if self.update:
            self._count += 1.0
            delta = obs - self._mean
            self._mean = self._mean + delta / self._count
            self._m2 = self._m2 + delta * (obs - self._mean)
        var = self._m2 / max(self._count, 1.0)
        out = (obs - self._mean) / np.sqrt(var + self.eps)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def get_state(self):
        return {"count": self._count, "mean": self._mean, "m2": self._m2}

    def set_state(self, state):
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class FrameStack(Connector):
    """Concatenate the last k observations (zero-padded at episode start)."""

    def __init__(self, k: int = 4):
        self.k = k
        self._frames: List[np.ndarray] = []

    def __call__(self, obs):
        obs = np.asarray(obs, np.float32)
        if not self._frames:
            self._frames = [np.zeros_like(obs) for _ in range(self.k)]
        self._frames = self._frames[1:] + [obs]
        return np.concatenate(self._frames, axis=-1)

    def reset(self):
        self._frames = []

    def transformed_size(self, obs_size: int) -> int:
        return obs_size * self.k


# ---------------------------------------------------------- module-to-env
class ModuleToEnvConnector(Connector):
    """Action-path transform: what the policy emitted → what the env
    steps on (reference: rllib module-to-env connector pipeline). Same
    state/checkpoint contract as the obs side."""

    def __call__(self, action):
        raise NotImplementedError


class ActionLambda(ModuleToEnvConnector):
    def __init__(self, fn: Callable[[Any], Any]):
        self._fn = fn

    def __call__(self, action):
        return self._fn(action)


class ActionRepeat(ModuleToEnvConnector):
    """Sticky actions: repeat the previous action with prob p (the
    standard Atari stochasticity knob; state = last action)."""

    def __init__(self, p: float = 0.25, seed: int = 0):
        self.p = p
        self._rng = np.random.default_rng(seed)
        self._last = None

    def __call__(self, action):
        if self._last is not None and self._rng.random() < self.p:
            return self._last
        self._last = action
        return action

    def reset(self):
        self._last = None

    def get_state(self):
        return {"last": self._last}

    def set_state(self, state):
        self._last = state.get("last")


# ------------------------------------------------------------ learner side
class LearnerConnector:
    """Batch-level transform applied just before the learner update
    (reference: rllib learner connector pipeline). Operates on the whole
    train-batch dict; stateful pieces are checkpointable like the env
    side."""

    def __call__(self, batch: Dict[str, np.ndarray]
                 ) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class LearnerConnectorPipeline(LearnerConnector):
    def __init__(self, connectors: Optional[List[LearnerConnector]] = None):
        self.connectors = list(connectors or [])

    def __call__(self, batch):
        for c in self.connectors:
            batch = c(batch)
        return batch

    def get_state(self):
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state):
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])


class BatchLambda(LearnerConnector):
    def __init__(self, fn: Callable[[Dict], Dict]):
        self._fn = fn

    def __call__(self, batch):
        return self._fn(batch)


class AdvantageStandardizer(LearnerConnector):
    """Zero-mean/unit-std advantages per train batch (the standard PPO
    stabilizer, expressed as a connector so it is composable/removable)."""

    def __init__(self, key: str = "advantages", eps: float = 1e-8):
        self.key = key
        self.eps = eps

    def __call__(self, batch):
        if self.key in batch:
            adv = batch[self.key]
            batch = dict(batch)
            batch[self.key] = (adv - adv.mean()) / (adv.std() + self.eps)
        return batch


class RewardClip(LearnerConnector):
    """Clip rewards into [lo, hi] at train time (DQN-style stabilization)."""

    def __init__(self, lo: float = -1.0, hi: float = 1.0,
                 key: str = "rewards"):
        self.lo, self.hi, self.key = lo, hi, key

    def __call__(self, batch):
        if self.key in batch:
            batch = dict(batch)
            batch[self.key] = np.clip(batch[self.key], self.lo, self.hi)
        return batch


# --------------------------------------------------------------- sequences
def window_sequences(batch: Dict[str, np.ndarray], seq_len: int
                     ) -> Dict[str, np.ndarray]:
    """Cut a time-major batch of fragments into fixed-length training
    windows for recurrent learners (reference: the AddStatesFromEpisodes
    learner-connector piece + RNNSequencing).

    Input columns are (F, T, ...) — F whole rollout fragments of T steps —
    except ``state_in_*`` columns, which are the PER-STEP recorded
    recurrent state (F, T, ...). Output: every non-state column becomes
    (B, L, ...) with B = F * (T // L); each ``state_in_*`` column is
    sliced AT WINDOW STARTS only → (B, ...), so the learner injects the
    exact carried state the policy acted with (burn-in-free) and replays
    mid-window resets from the ``is_first`` column. A trailing remainder
    of T % L steps is dropped."""
    F, T = next(iter(batch.values())).shape[:2]
    L = int(seq_len)
    W = T // L
    if W == 0:
        raise ValueError(f"seq_len {L} exceeds fragment length {T}")
    out: Dict[str, np.ndarray] = {}
    for k, v in batch.items():
        v = np.asarray(v)[:, :W * L]
        if k.startswith("state_in_"):
            out[k] = v[:, ::L].reshape((F * W,) + v.shape[2:])
        else:
            out[k] = v.reshape((F * W, L) + v.shape[2:])
    return out


class SequenceWindower(LearnerConnector):
    """``window_sequences`` as a composable learner-connector piece."""

    def __init__(self, seq_len: int = 16):
        self.seq_len = seq_len

    def __call__(self, batch):
        return window_sequences(batch, self.seq_len)
