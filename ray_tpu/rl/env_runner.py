"""EnvRunner: rollout actor (reference
``rllib/env/single_agent_env_runner.py:68``, ``sample:147``).

Numpy-only process: steps its env with the inference copy of the policy,
keeps env state across sample() calls (truncation-free stitching), returns
fixed-size rollout fragments plus completed-episode returns for metrics.

Stateful modules (rl/module.py contract): the runner carries per-episode
recurrent state across sample() calls, flags ``is_first`` rows so the
module resets exactly at episode starts, and emits per-step ``state_in``
columns (the PRE-step carried state) plus ``is_first`` in every fragment —
sequence learners inject the recorded state at window starts instead of
burning in.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

import numpy as np

from ray_tpu.rl.envs import make_env
from ray_tpu.rl.module import Params, np_sample_action


def _make_connector(c):
    """Accept a Connector instance, a Connector subclass, or a zero-arg
    factory — factories/classes build per-runner instances (stateful
    connectors must not share state across runners by accident)."""
    from ray_tpu.rl.connectors import Connector

    if isinstance(c, Connector):
        return c
    return c()


class EnvRunner:
    def __init__(self, env_spec: Union[str, Any] = "CartPole-v1",
                 seed: int = 0, worker_index: int = 0,
                 connectors=None, num_envs: int = 1,
                 module_to_env_connectors=None,
                 record_next_obs: bool = False):
        from ray_tpu.rl.connectors import ConnectorPipeline

        self.num_envs = max(1, num_envs)
        # Off-policy TD consumers (DQN/SAC replay) need the TRUE successor
        # state per step; it doubles the fragment's obs payload, so it is
        # recorded only when the algorithm asks (on-policy GAE/v-trace and
        # the offline writer never read it).
        self._record_next_obs = record_next_obs
        # Vectorization (reference rllib/env/vector/): N env copies stepped
        # in lockstep with ONE batched policy forward per step — sampling
        # throughput stops walling on per-env matmul overhead.
        self.envs = [make_env(env_spec,
                              seed=seed + worker_index * 1000 + i)
                     for i in range(self.num_envs)]
        self.env = self.envs[0]  # back-compat alias
        self._rng = np.random.default_rng(seed * 100003 + worker_index)
        self._params: Optional[Params] = None
        # env-to-module pipeline: raw obs -> what the policy consumes
        # (reference connector_v2 env-runner pipeline)
        self._pipeline = ConnectorPipeline(
            [_make_connector(c) for c in (connectors or [])])
        # module-to-env pipeline: policy action -> env action
        self._m2e = ConnectorPipeline(
            [_make_connector(c) for c in (module_to_env_connectors or [])])
        obs0 = []
        for i, env in enumerate(self.envs):
            raw, _ = env.reset(seed=seed + worker_index * 1000 + i)
            obs0.append(self._pipeline(raw))
        self._obs = obs0[0]
        self._obs_vec = np.stack(obs0)
        self._episode_return = 0.0
        self._episode_returns_vec = np.zeros(self.num_envs)
        self._weights_version = -1
        # recurrent-module state: carried across sample() calls, reset
        # per env on is_first (lazily sized once params are known)
        self._policy_state = None
        self._is_first_vec = np.ones(self.num_envs, bool)

    def get_connector_state(self):
        if self._m2e.connectors:
            return {"env_to_module": self._pipeline.get_state(),
                    "module_to_env": self._m2e.get_state()}
        return self._pipeline.get_state()

    def set_connector_state(self, state) -> bool:
        if isinstance(state, dict) and "env_to_module" in state:
            self._pipeline.set_state(state["env_to_module"])
            self._m2e.set_state(state.get("module_to_env", {}))
        else:
            self._pipeline.set_state(state)
        return True

    def ping(self) -> bool:
        return True

    def set_weights(self, params: Params, version: int = 0) -> bool:
        self._params = params
        self._weights_version = version
        return True

    def get_weights_version(self) -> int:
        return self._weights_version

    def sample(self, num_steps: int):
        """One fragment dict for num_envs == 1 (back-compat), else a LIST
        of per-env fragment dicts — each a normal fragment, so every
        consumer (GAE, aggregators, v-trace) is unchanged. Stateful
        modules always take the vector path (state is batched per env)."""
        from ray_tpu.rl.module import is_stateful

        stateful = self._params is not None and is_stateful(self._params)
        if self.num_envs > 1 or stateful:
            if self.num_envs == 1:
                self._episode_returns_vec[0] = self._episode_return
            frags = self._sample_vector(num_steps)
            if self.num_envs == 1:
                # keep the single-env aliases fresh in case a later
                # weights broadcast switches back to a feedforward module
                self._obs = self._obs_vec[0]
                self._episode_return = float(self._episode_returns_vec[0])
                return frags[0]
            return frags
        frag = self._sample_single(num_steps)
        self._obs_vec[0] = self._obs
        self._episode_returns_vec[0] = self._episode_return
        return frag

    def _ensure_policy_state(self):
        """(Re)allocate carried state when params first arrive or change
        family/shape; fresh state restarts every env as is_first."""
        from ray_tpu.rl.module import get_initial_state

        init = get_initial_state(self._params, self.num_envs)
        cur = self._policy_state
        if (cur is None or set(cur) != set(init)
                or any(cur[k].shape != init[k].shape for k in init)):
            self._policy_state = init
            self._is_first_vec = np.ones(self.num_envs, bool)

    def _sample_vector(self, num_steps: int):
        from ray_tpu.rl.module import (
            action_spec, is_continuous, is_stateful, np_forward,
            np_sample_actions_batch, np_sample_continuous_batch,
            np_stateful_sample_batch, np_stateful_values)

        assert self._params is not None, "set_weights first"
        N = self.num_envs
        cont = is_continuous(self._params)
        stateful = is_stateful(self._params)
        a_shape, a_dtype = action_spec(self._params)
        sampler = (np_sample_continuous_batch if cont
                   else np_sample_actions_batch)
        obs_buf = np.empty((N, num_steps) + self._obs_vec.shape[1:],
                           np.float32)
        act_buf = np.empty((N, num_steps) + a_shape, a_dtype)
        rew_buf = np.empty((N, num_steps), np.float32)
        done_buf = np.empty((N, num_steps), np.bool_)
        term_buf = np.empty((N, num_steps), np.bool_)
        next_obs_buf = (np.empty_like(obs_buf) if self._record_next_obs
                        else None)
        logp_buf = np.empty((N, num_steps), np.float32)
        val_buf = np.empty((N, num_steps), np.float32)
        episode_returns = [[] for _ in range(N)]
        state_bufs = first_buf = None
        if stateful:
            self._ensure_policy_state()
            state_bufs = {
                k: np.empty((N, num_steps) + v.shape[1:], np.float32)
                for k, v in self._policy_state.items()}
            first_buf = np.empty((N, num_steps), np.bool_)

        for t in range(num_steps):
            if stateful:
                # record the PRE-step carried state + is_first flag; the
                # module applies its own reset internally, and sequence
                # learners replay the exact same reset from these columns
                first_buf[:, t] = self._is_first_vec
                for k, v in self._policy_state.items():
                    state_bufs[k][:, t] = v
                actions, logps, values, self._policy_state = \
                    np_stateful_sample_batch(
                        self._params, self._obs_vec, self._policy_state,
                        self._is_first_vec, self._rng)
                self._is_first_vec[:] = False
            else:
                actions, logps, values = sampler(
                    self._params, self._obs_vec, self._rng)
            obs_buf[:, t] = self._obs_vec
            act_buf[:, t] = actions
            logp_buf[:, t] = logps
            val_buf[:, t] = values
            for i, env in enumerate(self.envs):
                raw, reward, terminated, truncated, _ = env.step(
                    self._m2e(actions[i] if cont else int(actions[i])))
                self._obs_vec[i] = self._pipeline(raw)
                rew_buf[i, t] = reward
                done_buf[i, t] = terminated or truncated
                # TD consumers need the TRUE successor state (pre-reset)
                # and termination distinct from time-limit truncation
                term_buf[i, t] = terminated
                if next_obs_buf is not None:
                    next_obs_buf[i, t] = self._obs_vec[i]
                self._episode_returns_vec[i] += reward
                if terminated or truncated:
                    episode_returns[i].append(
                        float(self._episode_returns_vec[i]))
                    self._episode_returns_vec[i] = 0.0
                    if self.num_envs == 1:
                        # single-env semantics (stateful modules route
                        # here too): episodic connectors flush exactly
                        # as in _sample_single. With N > 1 the pipeline
                        # is shared across envs, so per-env resets stay
                        # undefined (pre-existing vector behavior).
                        self._pipeline.reset()
                        self._m2e.reset()
                    raw, _ = env.reset()
                    self._obs_vec[i] = self._pipeline(raw)
                    self._is_first_vec[i] = True

        if cont:     # off-policy consumers bootstrap from their critics
            last_vals = np.zeros(N, np.float32)
        elif stateful:
            last_vals = np_stateful_values(
                self._params, self._obs_vec, self._policy_state,
                self._is_first_vec)
        else:
            _, last_vals = np_forward(self._params, self._obs_vec)
        out = []
        for i in range(N):
            frag = {"obs": obs_buf[i], "actions": act_buf[i],
                    "rewards": rew_buf[i], "dones": done_buf[i],
                    "terminated": term_buf[i],
                    "logp": logp_buf[i], "values": val_buf[i],
                    "last_value": float(last_vals[i]),
                    "episode_returns": episode_returns[i],
                    "weights_version": self._weights_version}
            if next_obs_buf is not None:
                frag["next_obs"] = next_obs_buf[i]
            if stateful:
                frag["state_in"] = {k: v[i] for k, v in state_bufs.items()}
                frag["is_first"] = first_buf[i]
            out.append(frag)
        return out

    def _sample_single(self, num_steps: int) -> Dict[str, Any]:
        from ray_tpu.rl.module import (
            action_spec, is_continuous, np_sample_continuous_batch)

        assert self._params is not None, "set_weights first"
        cont = is_continuous(self._params)
        a_shape, a_dtype = action_spec(self._params)
        obs_buf = np.empty((num_steps,) + self._obs.shape, np.float32)
        act_buf = np.empty((num_steps,) + a_shape, a_dtype)
        rew_buf = np.empty(num_steps, np.float32)
        done_buf = np.empty(num_steps, np.bool_)      # episode boundary
        term_buf = np.empty(num_steps, np.bool_)      # true termination
        next_obs_buf = (np.empty_like(obs_buf) if self._record_next_obs
                        else None)
        logp_buf = np.empty(num_steps, np.float32)
        val_buf = np.empty(num_steps, np.float32)
        episode_returns = []

        for t in range(num_steps):
            if cont:
                a_b, lp_b, v_b = np_sample_continuous_batch(
                    self._params, self._obs[None], self._rng)
                action, logp, value = a_b[0], float(lp_b[0]), float(v_b[0])
            else:
                action, logp, value = np_sample_action(
                    self._params, self._obs, self._rng)
            obs_buf[t] = self._obs
            act_buf[t] = action
            logp_buf[t] = logp
            val_buf[t] = value
            raw, reward, terminated, truncated, _ = self.env.step(
                self._m2e(action))
            self._obs = self._pipeline(raw)
            rew_buf[t] = reward
            # Truncation treated as termination for GAE (standard
            # simplification: no next-state bootstrap at the cut); TD
            # consumers get the distinct `terminated` flag + the TRUE
            # (pre-reset) successor state instead.
            done_buf[t] = terminated or truncated
            term_buf[t] = terminated
            if next_obs_buf is not None:
                next_obs_buf[t] = self._obs
            self._episode_return += reward
            if terminated or truncated:
                episode_returns.append(self._episode_return)
                self._episode_return = 0.0
                self._pipeline.reset()
                self._m2e.reset()
                raw, _ = self.env.reset()
                self._obs = self._pipeline(raw)

        # Bootstrap value for the (possibly mid-episode) final state.
        from ray_tpu.rl.module import np_forward

        if cont:     # off-policy consumers bootstrap from their critics
            last_val = np.zeros(1, np.float32)
        else:
            _, last_val = np_forward(self._params, self._obs[None])
        frag = {
            "obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
            "dones": done_buf, "terminated": term_buf,
            "logp": logp_buf, "values": val_buf,
            "last_value": float(last_val[0]),
            "episode_returns": episode_returns,
            "weights_version": self._weights_version,
        }
        if next_obs_buf is not None:
            frag["next_obs"] = next_obs_buf
        return frag
