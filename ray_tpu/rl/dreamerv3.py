"""DreamerV3: model-based RL — world model + imagination actor-critic.

Reference: ``rllib/algorithms/dreamerv3/dreamerv3.py`` (+
``dreamerv3_learner.py`` / ``dreamerv3_rl_module.py`` and the TF models
under ``utils/``).  The defining machinery is reproduced in JAX:

- **RSSM world model** — GRU deterministic state ``h`` + discrete latent
  ``z`` (categoricals × classes, unimix 1%, straight-through gradients),
  encoder/decoder (symlog MSE), reward head (symlog twohot), continue
  head; KL with free bits and dyn/rep balancing (0.5 / 0.1).
- **Imagination training** — H-step rollouts in latent space from
  replayed posteriors; λ-returns; twohot critic with EMA regularizer;
  actor trained on percentile-normalized advantages with entropy bonus.
- **Sequence replay** — (B, L) windows of real experience, is_first
  resets.

TPU framing: the ENTIRE update — world-model unroll (lax.scan over L),
imagination rollout (lax.scan over H), and both heads — is ONE jitted
function; every matmul is batched (B×L collapsed) for the MXU, and the
python loop never touches a per-step value.

Acting runs on the TRUE RSSM posterior latent through the stateful-module
channel (rl/module.py): :meth:`DreamerV3Learner.get_runner_weights`
exports the inference slice of the world model (GRU advance + encoder +
posterior + actor) as a numpy param dict, env runners carry the
(h, z, a) latent per episode and reset it on ``is_first`` exactly as the
trainer does, and replayed fragments record the per-step latent so
sequence windows inject the ACTED state at window starts instead of
burning in from zeros.  The actor/critic themselves train purely in
imagination, as in the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.replay import SequenceReplay  # noqa: F401  (re-export)

# ---------------------------------------------------------------- helpers


def _symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def _symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


_NUM_BINS = 41


def _bins():
    import jax.numpy as jnp

    return jnp.linspace(-10.0, 10.0, _NUM_BINS)  # symlog space


def _twohot(x):
    """Scalar (already symlog'd) → two-hot distribution over _bins()."""
    import jax
    import jax.numpy as jnp

    b = _bins()
    x = jnp.clip(x, b[0], b[-1])
    idx = jnp.clip(jnp.searchsorted(b, x, side="right") - 1, 0,
                   _NUM_BINS - 2)
    lo, hi = b[idx], b[idx + 1]
    w_hi = (x - lo) / (hi - lo)
    oh_lo = jax.nn.one_hot(idx, _NUM_BINS)
    oh_hi = jax.nn.one_hot(idx + 1, _NUM_BINS)
    return oh_lo * (1.0 - w_hi)[..., None] + oh_hi * w_hi[..., None]


def _twohot_mean(logits):
    """Expected value (in symexp space) of a twohot head."""
    import jax
    import jax.numpy as jnp

    probs = jax.nn.softmax(logits, axis=-1)
    return _symexp(jnp.sum(probs * _bins(), axis=-1))


def _dense_init(rng, fan_in, fan_out, scale=None):
    scale = np.sqrt(2.0 / fan_in) if scale is None else scale
    return ((rng.standard_normal((fan_in, fan_out)) * scale)
            .astype(np.float32), np.zeros(fan_out, np.float32))


def _mlp(params, prefix, x, n_layers):
    import jax.numpy as jnp

    for i in range(n_layers):
        x = jnp.tanh(x @ params[f"{prefix}{i}_w"] + params[f"{prefix}{i}_b"])
    return x


# ---------------------------------------------------------------- learner


class DreamerV3Learner:
    """Jitted world-model + imagination actor-critic update."""

    def __init__(self, obs_size: int, num_actions: int,
                 cfg: "DreamerV3Config"):
        import jax
        import optax

        self.cfg = cfg
        self.obs_size = obs_size
        self.num_actions = num_actions
        self.hid = cfg.units
        self.deter = cfg.deter
        self.cats = cfg.latent_categoricals
        self.classes = cfg.latent_classes
        self.zdim = self.cats * self.classes

        rng = np.random.default_rng(cfg.seed)
        p: Dict[str, np.ndarray] = {}

        def add(name, fi, fo, scale=None):
            p[f"{name}_w"], p[f"{name}_b"] = _dense_init(rng, fi, fo, scale)

        H, Z, U = self.deter, self.zdim, self.hid
        add("enc0", obs_size, U)
        add("post0", H + U, U)
        add("post_logits", U, Z, 0.01)
        add("prior0", H, U)
        add("prior_logits", U, Z, 0.01)
        # GRU: input [z, one_hot(action)] -> candidate/update/reset
        gin = Z + num_actions
        add("gru_x", gin, 3 * H)
        add("gru_h", H, 3 * H)
        add("dec0", H + Z, U)
        add("dec_out", U, obs_size, 0.01)
        # Reward/continue heads are ACTION-conditioned — r(s, a), c(s, a)
        # — a deliberate divergence from the reference's state-only heads:
        # this framework's fragments key rewards[t]/terminated[t] to the
        # OUTGOING transition (obs_t, a_t) and never record the terminal
        # arrival observation (runners reset in place), so a state-only
        # head cannot see which action ends the episode. Without the
        # action input the continue head stays uniformly optimistic,
        # imagination never terminates, and the actor gets no
        # differential signal (the observed 24-return plateau).
        add("rew0", H + Z + num_actions, U)
        add("rew_logits", U, _NUM_BINS, 0.0)  # zero-init (reference)
        add("cont0", H + Z + num_actions, U)
        add("cont_logit", U, 1, 0.01)
        add("actor0", H + Z, U)
        add("actor_logits", U, num_actions, 0.01)
        add("critic0", H + Z, U)
        add("critic_logits", U, _NUM_BINS, 0.0)

        self._params = jax.device_put(p)
        self._critic_ema = jax.device_put(
            {k: p[k] for k in ("critic0_w", "critic0_b",
                               "critic_logits_w", "critic_logits_b")})
        self._opt = optax.chain(optax.clip_by_global_norm(100.0),
                                optax.adam(cfg.lr))
        self._opt_state = self._opt.init(self._params)
        self._key = jax.random.key(cfg.seed)
        self._step = self._build_step()
        self._updates = 0

    # -------------------------------------------------------------- model
    def _unimix(self, logits):
        """1% uniform mixture on categorical probs (reference unimix)."""
        import jax
        import jax.numpy as jnp

        B = logits.shape[:-1]
        lg = logits.reshape(*B, self.cats, self.classes)
        probs = jax.nn.softmax(lg, axis=-1)
        probs = 0.99 * probs + 0.01 / self.classes
        return jnp.log(probs)

    def _sample_z(self, key, logits):
        """Straight-through categorical sample → flat one-hot (B, zdim)."""
        import jax
        import jax.numpy as jnp

        B = logits.shape[:-1]
        lg = logits.reshape(*B, self.cats, self.classes)
        idx = jax.random.categorical(key, lg, axis=-1)
        onehot = jax.nn.one_hot(idx, self.classes)
        probs = jax.nn.softmax(lg, axis=-1)
        st = onehot + probs - jax.lax.stop_gradient(probs)
        return st.reshape(*B, self.zdim)

    def _gru(self, p, h, z, a_onehot):
        import jax
        import jax.numpy as jnp

        D = self.deter
        x = jnp.concatenate([z, a_onehot], -1)
        gx = x @ p["gru_x_w"] + p["gru_x_b"]
        gh = h @ p["gru_h_w"] + p["gru_h_b"]
        r = jax.nn.sigmoid(gx[..., :D] + gh[..., :D])
        u = jax.nn.sigmoid(gx[..., D:2 * D] + gh[..., D:2 * D])
        c = jnp.tanh(gx[..., 2 * D:] + r * gh[..., 2 * D:])
        return u * c + (1.0 - u) * h

    def _build_step(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.cfg
        A = self.num_actions
        H, Z = self.deter, self.zdim

        def kl_cat(lhs_logits, rhs_logits):
            """KL( Cat(lhs) || Cat(rhs) ) summed over categoricals."""
            B = lhs_logits.shape[:-1]
            l1 = lhs_logits.reshape(*B, self.cats, self.classes)
            l2 = rhs_logits.reshape(*B, self.cats, self.classes)
            p1 = jax.nn.softmax(l1, -1)
            return jnp.sum(
                p1 * (jax.nn.log_softmax(l1, -1)
                      - jax.nn.log_softmax(l2, -1)), axis=(-2, -1))

        def heads(p, h, z, a_oh):
            hz = jnp.concatenate([h, z], -1)
            hza = jnp.concatenate([hz, a_oh], -1)
            dec = _mlp(p, "dec", hz, 1) @ p["dec_out_w"] + p["dec_out_b"]
            rew = _mlp(p, "rew", hza, 1) @ p["rew_logits_w"] \
                + p["rew_logits_b"]
            cont = (_mlp(p, "cont", hza, 1) @ p["cont_logit_w"]
                    + p["cont_logit_b"])[..., 0]
            return dec, rew, cont

        def critic_logits(cp, h, z):
            hz = jnp.concatenate([h, z], -1)
            x = jnp.tanh(hz @ cp["critic0_w"] + cp["critic0_b"])
            return x @ cp["critic_logits_w"] + cp["critic_logits_b"]

        def actor_logits(p, h, z):
            hz = jnp.concatenate([h, z], -1)
            lg = _mlp(p, "actor", hz, 1) @ p["actor_logits_w"] \
                + p["actor_logits_b"]
            # 1% unimix on the ACTION distribution too (reference actor)
            probs = 0.99 * jax.nn.softmax(lg, -1) + 0.01 / A
            return jnp.log(probs)

        def loss_fn(p, ema, key, batch):
            B, L = batch["actions"].shape
            obs = _symlog(batch["obs"])               # (B, L, obs)
            a_oh = jax.nn.one_hot(batch["actions"], A)
            keys = jax.random.split(key, L + 1)

            def wm_step(carry, t):
                h, z = carry
                # action a_{t-1} advances the state, then posterior sees
                # obs_t (reference sequence model contract); at the
                # window start the replayed pre-window action applies —
                # the same advance the acting tower performed
                a_prev = jnp.where(
                    t == 0, batch["state_in_a"], a_oh[:, t - 1])
                h = self._gru(p, h, z, a_prev)
                h = jnp.where(batch["is_first"][:, t, None], 0.0, h)
                e = _mlp(p, "enc", obs[:, t], 1)
                post = self._unimix(
                    _mlp(p, "post", jnp.concatenate([h, e], -1), 1)
                    @ p["post_logits_w"] + p["post_logits_b"]).reshape(
                        B, Z)
                prior = self._unimix(
                    _mlp(p, "prior", h, 1)
                    @ p["prior_logits_w"] + p["prior_logits_b"]).reshape(
                        B, Z)
                z = self._sample_z(keys[t], post)
                return (h, z), (h, z, post, prior)

            # burn-in-free window starts: the replay ships the latent the
            # policy ACTED with (zeros when unavailable, e.g. hand-built
            # batches), so mid-episode windows resume, not restart
            h0 = batch["state_in_h"]
            z0 = batch["state_in_z"]
            (_, _), (hs, zs, posts, priors) = jax.lax.scan(
                wm_step, (h0, z0), jnp.arange(L))
            # scan stacks on axis 0: (L, B, ·) -> (B, L, ·)
            hs, zs = hs.swapaxes(0, 1), zs.swapaxes(0, 1)
            posts, priors = posts.swapaxes(0, 1), priors.swapaxes(0, 1)

            dec, rew_logits, cont_logit = heads(p, hs, zs, a_oh)
            recon = jnp.mean(jnp.sum((dec - obs) ** 2, -1))
            rew_target = _twohot(_symlog(batch["rewards"]))
            rew_nll = -jnp.mean(jnp.sum(
                rew_target * jax.nn.log_softmax(rew_logits, -1), -1))
            cont_target = 1.0 - batch["terminated"]
            cont_nll = jnp.mean(
                optax.sigmoid_binary_cross_entropy(cont_logit,
                                                   cont_target))
            # KL: free bits + dyn/rep balancing (reference 0.5 / 0.1)
            dyn = jnp.maximum(1.0, kl_cat(
                jax.lax.stop_gradient(posts), priors)).mean()
            rep = jnp.maximum(1.0, kl_cat(
                posts, jax.lax.stop_gradient(priors))).mean()
            wm_loss = recon + rew_nll + cont_nll + 0.5 * dyn + 0.1 * rep

            # ---------------- imagination rollout (actor-critic) ------
            flat_h = jax.lax.stop_gradient(hs.reshape(B * L, H))
            flat_z = jax.lax.stop_gradient(zs.reshape(B * L, Z))
            ikeys = jax.random.split(keys[L], cfg.horizon)

            def img_step(carry, k):
                h, z = carry
                k_a, k_z = jax.random.split(k)  # independent draws: a
                # shared key would correlate the imagined action with the
                # imagined transition, biasing returns
                alog = actor_logits(p, h, z)
                a = jax.random.categorical(k_a, alog, -1)
                a_oh_i = jax.nn.one_hot(a, A)
                h2 = self._gru(p, h, z, a_oh_i)
                prior = self._unimix(
                    _mlp(p, "prior", h2, 1)
                    @ p["prior_logits_w"] + p["prior_logits_b"]).reshape(
                        h2.shape[0], Z)
                z2 = self._sample_z(k_z, prior)
                return (h2, z2), (h, z, alog, a)

            (_, _), (ih, iz, ialog, ia) = jax.lax.scan(
                img_step, (flat_h, flat_z), ikeys)
            # (Hor, BL, ·)
            _, irew_logits, icont_logit = heads(p, ih, iz,
                                                jax.nn.one_hot(ia, A))
            irew = _twohot_mean(irew_logits)
            icont = jax.nn.sigmoid(icont_logit)
            ival = _twohot_mean(critic_logits(p, ih, iz))
            ival_ema = _twohot_mean(critic_logits(ema, ih, iz))

            disc = cfg.gamma * icont
            # λ-returns backward over the horizon, bootstrapping on the
            # NEXT state's value: R_t = r_t + γc_t((1-λ)v_{t+1} + λR_{t+1})
            next_val = jnp.concatenate([ival[1:], ival[-1:]], 0)

            def lam_step(nxt, t):
                r = irew[t] + disc[t] * (
                    (1.0 - cfg.lmbda) * next_val[t] + cfg.lmbda * nxt)
                return r, r

            _, rets = jax.lax.scan(lam_step, ival[-1],
                                   jnp.arange(cfg.horizon - 1, -1, -1))
            rets = rets[::-1]                       # (Hor, BL)

            # critic: twohot NLL to λ-returns + EMA regularizer
            tgt = jax.lax.stop_gradient(_twohot(_symlog(rets)))
            clog = critic_logits(p, ih, iz)
            critic_nll = -jnp.mean(jnp.sum(
                tgt * jax.nn.log_softmax(clog, -1), -1))
            ema_reg = -jnp.mean(jnp.sum(
                jax.lax.stop_gradient(
                    jax.nn.softmax(critic_logits(ema, ih, iz), -1))
                * jax.nn.log_softmax(clog, -1), -1))
            critic_loss = critic_nll + cfg.critic_ema_reg * ema_reg

            # actor: percentile-normalized advantages (reference S)
            adv = rets - ival_ema
            lo = jnp.percentile(rets, 5.0)
            hi = jnp.percentile(rets, 95.0)
            scale = jnp.maximum(1.0, hi - lo)
            logp = jax.nn.log_softmax(ialog, -1)
            taken = jnp.take_along_axis(logp, ia[..., None], -1)[..., 0]
            ent = -jnp.sum(jax.nn.softmax(ialog, -1) * logp, -1)
            actor_loss = jnp.mean(
                -jax.lax.stop_gradient(adv / scale) * taken
                - cfg.entropy_coeff * ent)

            total = wm_loss + critic_loss + actor_loss
            # continue-head calibration diagnostics: a healthy model
            # separates these; both near 1.0 means imagination never
            # terminates and the actor trains against a delusion
            cont_p = jax.nn.sigmoid(cont_logit)
            term = batch["terminated"]
            p_term = jnp.sum(cont_p * term) / jnp.maximum(term.sum(), 1.0)
            p_alive = jnp.sum(cont_p * (1 - term)) / jnp.maximum(
                (1 - term).sum(), 1.0)
            aux = {"wm_loss": wm_loss, "recon": recon, "rew_nll": rew_nll,
                   "kl_dyn": dyn, "critic_loss": critic_loss,
                   "actor_loss": actor_loss,
                   "cont_p_at_term": p_term, "cont_p_alive": p_alive,
                   "imag_disc_mean": icont.mean(),
                   "imagined_return_mean": rets.mean()}
            return total, aux

        @jax.jit
        def step(params, ema, opt_state, key, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, ema, key, batch)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            ema = jax.tree.map(
                lambda e, q: 0.98 * e + 0.02 * q, ema,
                {k: params[k] for k in ema})
            return params, ema, opt_state, loss, aux

        return step

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        self._key, sub = jax.random.split(self._key)
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        jb["rewards"] = jb["rewards"].astype(jnp.float32)
        jb["terminated"] = jb["terminated"].astype(jnp.float32)
        jb["is_first"] = jb["is_first"].astype(jnp.bool_)
        # window-start latent injection; zero fallback for batches built
        # without recorded acting state (unit tests, external data)
        B = jb["actions"].shape[0]
        for k, dim in (("state_in_h", self.deter), ("state_in_z", self.zdim),
                       ("state_in_a", self.num_actions)):
            if k in jb:
                jb[k] = jb[k].astype(jnp.float32)
            else:
                jb[k] = jnp.zeros((B, dim), jnp.float32)
        self._params, self._critic_ema, self._opt_state, loss, aux = \
            self._step(self._params, self._critic_ema, self._opt_state,
                       sub, jb)
        self._updates += 1
        return {"loss": float(loss),
                **{k: float(v) for k, v in aux.items()}}

    # the inference-only slice of the world model a runner needs to act
    # on the true posterior latent (rl/module.py RSSM family)
    _ACTING_KEYS = ("enc0_w", "enc0_b", "post0_w", "post0_b",
                    "post_logits_w", "post_logits_b",
                    "gru_x_w", "gru_x_b", "gru_h_w", "gru_h_b",
                    "actor0_w", "actor0_b",
                    "actor_logits_w", "actor_logits_b")

    def get_runner_weights(self) -> Dict[str, np.ndarray]:
        """The RSSM acting tower in the rl/module.py stateful schema:
        env runners thread the (h, z, a) latent through
        ``np_stateful_sample_batch`` and act on the actor's true
        posterior-conditioned distribution — no distillate."""
        out = {k: np.asarray(self._params[k]) for k in self._ACTING_KEYS}
        out["rssm_meta"] = np.asarray([self.cats, self.classes], np.int32)
        return out


# -------------------------------------------------------------- algorithm
# (SequenceReplay lives in rl/replay.py — shared with other sequence
# learners — and is re-exported above for back-compat.)


class DreamerV3(Algorithm):
    """Sample real steps (acting on the RSSM posterior latent) → sequence
    replay with recorded latents → world-model + imagination updates →
    broadcast the refreshed acting tower."""

    def __init__(self, config: "DreamerV3Config"):
        super().__init__(config)
        self.learner = DreamerV3Learner(
            self._env_probe["obs_size"], self._env_probe["num_actions"],
            config)
        self.replay = SequenceReplay(config.replay_capacity,
                                     config.seq_len, seed=config.seed)

    def get_weights(self):
        return self.learner.get_runner_weights()

    def training_step(self) -> Dict[str, Any]:
        cfg: DreamerV3Config = self.config  # type: ignore[assignment]
        fragments = self._sample_fragments()
        if not fragments:
            raise RuntimeError("no healthy env runners produced samples")
        returns: List[float] = []
        for f in fragments:
            self.replay.add_fragment(f)
            returns.extend(f["episode_returns"])
        metrics: Dict[str, float] = {}
        if len(self.replay) >= cfg.learning_starts and \
                self.replay.has_sequences(cfg.batch_size):
            for _ in range(cfg.updates_per_iteration):
                metrics = self.learner.update(
                    self.replay.sample(cfg.batch_size))
        self._weights_version += 1
        self._return_window = (self._return_window + returns)[-100:]
        return {
            "env_runners": {
                "episode_return_mean": self.episode_return_mean(),
                "num_episodes": len(returns),
                "num_env_steps_sampled": sum(
                    len(f["obs"]) for f in fragments),
                "num_healthy_workers":
                    self.env_runner_group.num_healthy_actors(),
            },
            "learners": {"default_policy": metrics},
            "replay_buffer_size": len(self.replay),
        }


@dataclasses.dataclass
class DreamerV3Config(AlgorithmConfig):
    # 1e-3 (vs the reference's ~4e-4 for far bigger nets): at this tiny
    # scale the world model is the wall-clock bottleneck for CI-budget
    # learning, and the smaller nets tolerate the hotter rate
    lr: float = 1e-3
    gamma: float = 0.997
    lmbda: float = 0.95
    horizon: int = 15
    seq_len: int = 16
    batch_size: int = 16
    units: int = 64
    deter: int = 64
    latent_categoricals: int = 8
    latent_classes: int = 8
    entropy_coeff: float = 3e-3
    critic_ema_reg: float = 1.0
    replay_capacity: int = 100_000
    learning_starts: int = 500
    updates_per_iteration: int = 8
    algo_class = DreamerV3
