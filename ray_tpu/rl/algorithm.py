"""Algorithm: the sample→learn control loop.

Reference: ``rllib/algorithms/algorithm.py:207`` (Algorithm),
``algorithm_config.py`` (builder-style config), PPO ``training_step`` at
``rllib/algorithms/ppo/ppo.py:388``: fan out sampling to the EnvRunner
fleet via FaultTolerantActorManager, update the learner, broadcast weights.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ray_tpu.rl.actor_manager import FaultTolerantActorManager
from ray_tpu.rl.env_runner import EnvRunner
from ray_tpu.rl.learner import PPOLearner, build_ppo_batch, compute_gae  # noqa: F401 — compute_gae re-exported for existing importers
from ray_tpu.rl.module import init_lstm_policy_params, init_policy_params


@dataclasses.dataclass
class AlgorithmConfig:
    env: Union[str, Any] = "CartPole-v1"
    # factories producing fresh Connector instances per env runner
    connectors: tuple = ()
    # module-to-env (action-path) connector factories per env runner
    module_to_env_connectors: tuple = ()
    # learner-side batch connectors (applied just before each update)
    learner_connectors: tuple = ()
    num_env_runners: int = 2
    # vectorized envs per runner (reference num_envs_per_env_runner +
    # rllib/env/vector/): N env copies per actor, one batched policy
    # forward per step; sample() then returns N per-env fragments
    num_envs_per_env_runner: int = 1
    rollout_fragment_length: int = 256
    # record the true successor state per step in fragments (doubles the
    # obs payload; off-policy configs turn it on, on-policy never read it)
    record_next_obs: bool = False
    gamma: float = 0.99
    lr: float = 3e-4
    seed: int = 0
    # network
    hidden: tuple = (64, 64)
    # restart dead env runners on the next step
    restart_failed_env_runners: bool = True

    def environment(self, env) -> "AlgorithmConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners: int,
                    num_envs_per_env_runner: Optional[int] = None
                    ) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown training param {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "Algorithm":
        return self.algo_class(self)  # type: ignore[attr-defined]


class Algorithm:
    """Base sample→learn loop driver (reference ``Algorithm.step:986``)."""

    def __init__(self, config: AlgorithmConfig):
        import ray_tpu

        self.config = config
        self.iteration = 0
        self._weights_version = 0
        self._env_probe = _probe_env(config.env, config.connectors)
        remote_runner = ray_tpu.remote(EnvRunner)
        actors = [
            remote_runner.remote(
                config.env, seed=config.seed, worker_index=i,
                connectors=list(config.connectors),
                num_envs=getattr(config, "num_envs_per_env_runner", 1),
                module_to_env_connectors=list(
                    getattr(config, "module_to_env_connectors", ())),
                record_next_obs=getattr(config, "record_next_obs", False))
            for i in range(config.num_env_runners)
        ]
        self.env_runner_group = FaultTolerantActorManager(actors)
        self._return_window: List[float] = []
        from ray_tpu.rl.connectors import LearnerConnectorPipeline

        self._learner_pipeline = LearnerConnectorPipeline([
            c if not isinstance(c, type) else c()
            for c in getattr(config, "learner_connectors", ())])

    # -------------------------------------------------------------- train
    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        self.iteration += 1
        results = self.training_step()
        results.setdefault("training_iteration", self.iteration)
        results["time_this_iter_s"] = time.perf_counter() - t0
        return results

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def _maybe_restore_runners(self):
        if self.config.restart_failed_env_runners:
            self.env_runner_group.probe_health()

    def _sample_fragments(self) -> List[Dict[str, Any]]:
        self._maybe_restore_runners()
        version = self._weights_version
        weights = self.get_weights()
        self.env_runner_group.foreach_actor(
            lambda a: a.set_weights.remote(weights, version))
        results = self.env_runner_group.foreach_actor(
            lambda a: a.sample.remote(self.config.rollout_fragment_length))
        if self.config.connectors:
            # sync stateful connector stats (e.g. obs-normalizer running
            # mean/var) runner 0 -> fleet, so policies see one distribution
            states = self.env_runner_group.foreach_actor(
                lambda a: a.get_connector_state.remote())
            good = [r.value for r in states if r.ok]
            if good:
                self.env_runner_group.foreach_actor(
                    lambda a: a.set_connector_state.remote(good[0]))
        out: List[Dict[str, Any]] = []
        for r in results:
            if not r.ok:
                continue
            # vectorized runners return a LIST of per-env fragments
            out.extend(r.value if isinstance(r.value, list) else [r.value])
        return out

    def episode_return_mean(self) -> float:
        if not self._return_window:
            return float("nan")
        return float(np.mean(self._return_window[-100:]))

    def get_weights(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    # ---------------------------------------------------------- checkpoints
    def save_checkpoint(self, path: str) -> str:
        """Component-tree checkpoint (reference: Checkpointable mixin,
        rllib/utils/checkpoints.py — Algorithm -> Learner weights +
        connector states on BOTH the env-runner and learner sides)."""
        import os
        import pickle

        os.makedirs(path, exist_ok=True)
        runner_states = [
            r.value for r in self.env_runner_group.foreach_actor(
                lambda a: a.get_connector_state.remote())
            if r.ok
        ]
        state = {
            "weights": self.get_weights(),
            "iteration": self.iteration,
            "weights_version": self._weights_version,
            "return_window": list(self._return_window),
            "env_runner_connector_state": (runner_states[0]
                                           if runner_states else None),
            "learner_connector_state": self._learner_pipeline.get_state(),
        }
        # learners with state beyond the policy weights (SAC: critics,
        # targets, α, optimizer moments) checkpoint it all
        learner = getattr(self, "learner", None)
        if learner is not None and hasattr(learner, "get_state"):
            state["learner_state"] = learner.get_state()
        fname = os.path.join(path, "algorithm_state.pkl")
        with open(fname, "wb") as f:
            pickle.dump(state, f)
        return fname

    def restore_from_checkpoint(self, path: str) -> None:
        import os
        import pickle

        fname = (path if path.endswith(".pkl")
                 else os.path.join(path, "algorithm_state.pkl"))
        with open(fname, "rb") as f:
            state = pickle.load(f)
        learner = getattr(self, "learner", None)
        if state.get("learner_state") is not None and learner is not None \
                and hasattr(learner, "set_state"):
            learner.set_state(state["learner_state"])
        self.set_weights(state["weights"])
        self.iteration = state["iteration"]
        self._weights_version = state["weights_version"]
        self._return_window = list(state["return_window"])
        if state.get("env_runner_connector_state") is not None:
            cs = state["env_runner_connector_state"]
            self.env_runner_group.foreach_actor(
                lambda a: a.set_connector_state.remote(cs))
        self._learner_pipeline.set_state(
            state.get("learner_connector_state", {}))

    def set_weights(self, weights: Dict[str, np.ndarray]) -> None:
        learner = getattr(self, "learner", None)
        group = getattr(self, "learner_group", None)
        if group is not None:
            group.set_weights(weights)
        elif learner is not None:
            learner.set_weights(weights)
        else:
            raise NotImplementedError

    def stop(self):
        for i in list(self.env_runner_group.actors):
            self.env_runner_group.remove_actor(i)

    # ------------------------------------------------------------ scale-out
    def scale_out(self, podracer: "Any"):
        """Podracer scale-out (rl/podracer.py): Sebulba mode returns a
        live :class:`~ray_tpu.rl.podracer.SebulbaHandle` streaming
        fragments from dedicated runner actors into a learner actor;
        Anakin mode returns an :class:`~ray_tpu.rl.podracer.Anakin`
        running fully-jitted in-graph updates.  ``stop()``/``train()``
        fold the trained weights back into this algorithm."""
        from ray_tpu.rl.podracer import scale_out as _scale_out

        return _scale_out(self, podracer)


class PPO(Algorithm):
    def __init__(self, config: "PPOConfig"):
        super().__init__(config)
        if getattr(config, "module", "mlp") == "lstm":
            # recurrent policy (rl/module.py stateful contract); width is
            # the first entry of `hidden` — one cell, not a stack
            params = init_lstm_policy_params(
                self._env_probe["obs_size"],
                self._env_probe["num_actions"],
                hidden=int(config.hidden[0]), seed=config.seed)
        else:
            params = init_policy_params(
                self._env_probe["obs_size"],
                self._env_probe["num_actions"],
                hidden=tuple(config.hidden), seed=config.seed)
        self.learner = PPOLearner(
            params, lr=config.lr, clip=config.clip,
            vf_coeff=config.vf_coeff, entropy_coeff=config.entropy_coeff,
            num_epochs=config.num_epochs,
            minibatch_size=config.minibatch_size, seed=config.seed)

    def get_weights(self):
        return self.learner.get_weights()

    def training_step(self) -> Dict[str, Any]:
        fragments = self._sample_fragments()
        if not fragments:
            raise RuntimeError("no healthy env runners produced samples")
        batch, returns, env_steps = build_ppo_batch(
            fragments, gamma=self.config.gamma, lam=self.config.lam,
            seq_len=self.config.seq_len)
        batch = self._learner_pipeline(batch)
        metrics = self.learner.update(batch)
        self._weights_version += 1
        self._return_window = (self._return_window + returns)[-100:]
        return {
            "env_runners": {
                "episode_return_mean": self.episode_return_mean(),
                "num_episodes": len(returns),
                "num_env_steps_sampled": env_steps,
                "num_healthy_workers":
                    self.env_runner_group.num_healthy_actors(),
            },
            "learners": {"default_policy": metrics},
        }


@dataclasses.dataclass
class PPOConfig(AlgorithmConfig):
    lam: float = 0.95
    clip: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    minibatch_size: int = 128
    # module family: "mlp" (feedforward twin towers) or "lstm" (stateful
    # recurrent policy; training then uses (B, seq_len) windows)
    module: str = "mlp"
    seq_len: int = 16
    algo_class = PPO


def _probe_env(env_spec, connectors=()) -> Dict[str, int]:
    from ray_tpu.rl.connectors import ConnectorPipeline
    from ray_tpu.rl.envs import make_env

    env = make_env(env_spec)
    obs, _ = env.reset(seed=0)
    obs_size = int(np.asarray(obs).size)
    if connectors:
        from ray_tpu.rl.env_runner import _make_connector

        # size-only computation (output_size chains transformed_size):
        # running instances on a real obs would mutate stateful connector
        # INSTANCES that then ship contaminated to every runner
        pipeline = ConnectorPipeline([_make_connector(c)
                                      for c in connectors])
        obs_size = pipeline.output_size(obs_size)
    num_actions = getattr(env, "num_actions", None)
    if num_actions is None:
        space = getattr(env, "action_space", None)
        num_actions = getattr(space, "n", None)
    if num_actions is None:
        # continuous env: action_dim (+ symmetric bound) instead of a
        # discrete count (reference: Box vs Discrete action spaces)
        action_dim = getattr(env, "action_dim", None)
        if action_dim is None:
            raise ValueError(
                f"env {env_spec!r} exposes neither num_actions nor "
                "action_dim")
        return {"obs_size": obs_size, "continuous": True,
                "action_dim": int(action_dim),
                "action_scale": float(getattr(env, "action_high", 1.0))}
    return {"obs_size": obs_size, "num_actions": int(num_actions)}
