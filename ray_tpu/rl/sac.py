"""SAC: off-policy maximum-entropy actor-critic for continuous control.

Reference: ``rllib/algorithms/sac/sac.py`` (+ ``sac_learner.py`` /
``default_sac_rl_module.py``): twin soft Q-functions with polyak-averaged
targets, a tanh-squashed Gaussian actor, and learned entropy temperature
α against a -|A| target entropy. TPU framing: the whole update (critic +
actor + α + polyak) is ONE jitted function over a replayed minibatch —
four small MLP towers batched on the MXU; replay sampling stays host-side
numpy (same split as DQN).

Runner side: the actor's weights are module.py continuous-policy params,
so stock :class:`EnvRunner` actors sample exploration actions from the
squashed Gaussian with no SAC-specific code.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.replay import ReplayBuffer, transitions_from_fragment
from ray_tpu.rl.module import (
    LOGSTD_MAX, LOGSTD_MIN, init_continuous_policy_params)


def _init_q_params(obs_size: int, action_dim: int, hidden, seed: int,
                   prefix: str) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    params = {}
    sizes = (obs_size + action_dim,) + tuple(hidden)
    for i in range(len(hidden)):
        params[f"{prefix}{i}_w"] = (
            rng.standard_normal((sizes[i], sizes[i + 1]))
            * np.sqrt(2.0 / sizes[i])).astype(np.float32)
        params[f"{prefix}{i}_b"] = np.zeros(sizes[i + 1], np.float32)
    params[f"{prefix}h_w"] = (rng.standard_normal((sizes[-1], 1))
                              * 0.01).astype(np.float32)
    params[f"{prefix}h_b"] = np.zeros(1, np.float32)
    return params


class SACLearner:
    """Jitted twin-Q + squashed-Gaussian-actor + α update."""

    def __init__(self, obs_size: int, action_dim: int, *,
                 hidden=(64, 64), actor_lr: float = 3e-4,
                 critic_lr: float = 3e-4, alpha_lr: float = 3e-4,
                 gamma: float = 0.99, tau: float = 0.005,
                 action_scale: float = 1.0, seed: int = 0,
                 target_entropy: float = None):
        import optax

        self.gamma = gamma
        self.tau = tau
        self.action_dim = action_dim
        self.target_entropy = (-float(action_dim) if target_entropy is None
                               else target_entropy)
        self.actor = init_continuous_policy_params(
            obs_size, action_dim, hidden=tuple(hidden), seed=seed,
            action_scale=action_scale)
        self.q1 = _init_q_params(obs_size, action_dim, hidden, seed + 1,
                                 "q")
        self.q2 = _init_q_params(obs_size, action_dim, hidden, seed + 2,
                                 "q")
        self.q1_target = {k: v.copy() for k, v in self.q1.items()}
        self.q2_target = {k: v.copy() for k, v in self.q2.items()}
        self.log_alpha = np.zeros((), np.float32)
        self._opt_actor = optax.adam(actor_lr)
        self._opt_critic = optax.adam(critic_lr)
        self._opt_alpha = optax.adam(alpha_lr)
        # action_scale is a bound, not a weight: freeze it
        import jax

        self._actor_opt_state = self._opt_actor.init(
            {k: v for k, v in self.actor.items() if k != "action_scale"})
        self._critic_opt_state = self._opt_critic.init((self.q1, self.q2))
        self._alpha_opt_state = self._opt_alpha.init(self.log_alpha)
        self._step = self._build_step()
        self._key = jax.random.key(seed + 7)
        self._n_updates = 0

    @staticmethod
    def _q_forward(params, obs, act):
        import jax.numpy as jnp

        x = jnp.concatenate([obs, act], axis=1)
        i = 0
        while f"q{i}_w" in params:
            x = jnp.tanh(x @ params[f"q{i}_w"] + params[f"q{i}_b"])
            i += 1
        return (x @ params["qh_w"] + params["qh_b"])[:, 0]

    @staticmethod
    def _actor_dist(actor, obs):
        import jax.numpy as jnp

        x = obs
        i = 0
        while f"c{i}_w" in actor:
            x = jnp.tanh(x @ actor[f"c{i}_w"] + actor[f"c{i}_b"])
            i += 1
        mu = x @ actor["mu_w"] + actor["mu_b"]
        logstd = jnp.clip(x @ actor["ls_w"] + actor["ls_b"],
                          LOGSTD_MIN, LOGSTD_MAX)
        return mu, logstd

    @classmethod
    def _sample_squashed(cls, actor, obs, key):
        """Reparameterized tanh-Gaussian sample → (action, logp)."""
        import jax
        import jax.numpy as jnp

        mu, logstd = cls._actor_dist(actor, obs)
        std = jnp.exp(logstd)
        eps = jax.random.normal(key, mu.shape)
        pre = mu + std * eps
        scale = actor["action_scale"]
        act = jnp.tanh(pre) * scale
        logp = (-0.5 * (eps ** 2 + jnp.log(2 * jnp.pi)) - logstd
                - jnp.log(scale * (1 - jnp.tanh(pre) ** 2) + 1e-6)
                ).sum(axis=1)
        return act, logp

    def _conservative_penalty(self, qs, actor, batch, key):
        """Critic-loss addend hook; CQLLearner overrides (sac stays 0)."""
        return 0.0

    def _build_step(self):
        import jax
        import jax.numpy as jnp
        import optax

        gamma, tau, tgt_ent = self.gamma, self.tau, self.target_entropy
        opt_a, opt_c, opt_al = (self._opt_actor, self._opt_critic,
                                self._opt_alpha)
        qf, sample = self._q_forward, self._sample_squashed
        penalty = self._conservative_penalty

        def step(actor, q1, q2, q1_t, q2_t, log_alpha,
                 a_opt, c_opt, al_opt, batch, key):
            k1, k2, k3 = jax.random.split(key, 3)
            alpha = jnp.exp(log_alpha)

            # ---- critics: y = r + γ(1-d)(min Q'(s', a') - α logπ(a'|s'))
            a_next, logp_next = sample(actor, batch["next_obs"], k1)
            q_next = jnp.minimum(qf(q1_t, batch["next_obs"], a_next),
                                 qf(q2_t, batch["next_obs"], a_next))
            nonterm = 1.0 - batch["dones"].astype(jnp.float32)
            y = jax.lax.stop_gradient(
                batch["rewards"] + gamma * nonterm
                * (q_next - alpha * logp_next))

            def critic_loss(qs):
                p1, p2 = qs
                l1 = jnp.mean((qf(p1, batch["obs"], batch["actions"])
                               - y) ** 2)
                l2 = jnp.mean((qf(p2, batch["obs"], batch["actions"])
                               - y) ** 2)
                pen = penalty(qs, actor, batch, k3)
                return l1 + l2 + pen, (l1, l2, pen)

            (closs, (l1, l2, pen)), cgrads = jax.value_and_grad(
                critic_loss, has_aux=True)((q1, q2))
            cupd, c_opt = opt_c.update(cgrads, c_opt, (q1, q2))
            q1, q2 = optax.apply_updates((q1, q2), cupd)

            # ---- actor: max E[min Q(s, a~π) - α logπ]
            def actor_loss(a_train):
                a_full = dict(a_train, action_scale=actor["action_scale"])
                a_new, logp = sample(a_full, batch["obs"], k2)
                q_new = jnp.minimum(qf(q1, batch["obs"], a_new),
                                    qf(q2, batch["obs"], a_new))
                return jnp.mean(alpha * logp - q_new), logp

            a_train = {k: v for k, v in actor.items()
                       if k != "action_scale"}
            (aloss, logp_new), agrads = jax.value_and_grad(
                actor_loss, has_aux=True)(a_train)
            aupd, a_opt = opt_a.update(agrads, a_opt, a_train)
            a_train = optax.apply_updates(a_train, aupd)
            actor = dict(a_train, action_scale=actor["action_scale"])

            # ---- temperature: push E[logπ] toward -target_entropy
            def alpha_loss(la):
                return -jnp.mean(
                    la * jax.lax.stop_gradient(logp_new + tgt_ent))

            alloss, algrad = jax.value_and_grad(alpha_loss)(log_alpha)
            alupd, al_opt = opt_al.update(algrad, al_opt, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, alupd)

            # ---- polyak targets
            q1_t = jax.tree.map(lambda t, s: (1 - tau) * t + tau * s,
                                q1_t, q1)
            q2_t = jax.tree.map(lambda t, s: (1 - tau) * t + tau * s,
                                q2_t, q2)
            metrics = {"critic_loss": closs, "q1_loss": l1, "q2_loss": l2,
                       "actor_loss": aloss, "alpha_loss": alloss,
                       "alpha": alpha, "cql_penalty": pen,
                       "entropy": -jnp.mean(logp_new)}
            return (actor, q1, q2, q1_t, q2_t, log_alpha,
                    a_opt, c_opt, al_opt, metrics)

        return jax.jit(step)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        self._key, sub = jax.random.split(self._key)
        (self.actor, self.q1, self.q2, self.q1_target, self.q2_target,
         self.log_alpha, self._actor_opt_state, self._critic_opt_state,
         self._alpha_opt_state, metrics) = self._step(
            self.actor, self.q1, self.q2, self.q1_target, self.q2_target,
            self.log_alpha, self._actor_opt_state, self._critic_opt_state,
            self._alpha_opt_state, batch, sub)
        self._n_updates += 1
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self) -> Dict[str, np.ndarray]:
        """Exploration-policy weights for env runners (actor only)."""
        return {k: np.asarray(v) for k, v in self.actor.items()}

    def set_weights(self, weights: Dict[str, np.ndarray]) -> None:
        self.actor = {k: np.asarray(v) for k, v in weights.items()}

    def get_state(self) -> Dict[str, Any]:
        """FULL trainable state — critics, targets, α, optimizer states —
        for checkpointing (get_weights alone would resume the restored
        actor against fresh critics and destroy it within updates)."""
        import jax

        host = lambda t: jax.tree.map(np.asarray, t)  # noqa: E731
        return {"actor": host(self.actor), "q1": host(self.q1),
                "q2": host(self.q2), "q1_target": host(self.q1_target),
                "q2_target": host(self.q2_target),
                "log_alpha": np.asarray(self.log_alpha),
                "actor_opt": host(self._actor_opt_state),
                "critic_opt": host(self._critic_opt_state),
                "alpha_opt": host(self._alpha_opt_state),
                "n_updates": self._n_updates}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.actor = dict(state["actor"])
        self.q1 = dict(state["q1"])
        self.q2 = dict(state["q2"])
        self.q1_target = dict(state["q1_target"])
        self.q2_target = dict(state["q2_target"])
        self.log_alpha = state["log_alpha"]
        self._actor_opt_state = state["actor_opt"]
        self._critic_opt_state = state["critic_opt"]
        self._alpha_opt_state = state["alpha_opt"]
        self._n_updates = state.get("n_updates", 0)


class SAC(Algorithm):
    def __init__(self, config: "SACConfig"):
        super().__init__(config)
        probe = self._env_probe
        if not probe.get("continuous"):
            raise ValueError("SAC requires a continuous-action env "
                             "(action_dim attribute)")
        self.learner = SACLearner(
            probe["obs_size"], probe["action_dim"],
            hidden=tuple(config.hidden), actor_lr=config.lr,
            critic_lr=config.critic_lr, alpha_lr=config.alpha_lr,
            gamma=config.gamma, tau=config.tau,
            action_scale=probe.get("action_scale", 1.0),
            seed=config.seed)
        self.replay = ReplayBuffer(config.replay_capacity,
                                   seed=config.seed)
        self._env_steps = 0

    def get_weights(self):
        return self.learner.get_weights()

    def training_step(self) -> Dict[str, Any]:
        fragments = self._sample_fragments()
        if not fragments:
            raise RuntimeError("no healthy env runners produced samples")
        returns: List[float] = []
        new_steps = 0
        for f in fragments:
            self.replay.add_fragment(transitions_from_fragment(f))
            returns.extend(f["episode_returns"])
            new_steps += len(f["obs"])
        self._env_steps += new_steps

        metrics: Dict[str, float] = {}
        if len(self.replay) >= self.config.learning_starts:
            n_updates = max(1, int(new_steps
                                   * self.config.updates_per_env_step))
            for _ in range(n_updates):
                metrics = self.learner.update(
                    self.replay.sample(self.config.train_batch_size))
        self._weights_version += 1
        self._return_window = (self._return_window + returns)[-100:]
        return {
            "env_runners": {
                "episode_return_mean": self.episode_return_mean(),
                "num_episodes": len(returns),
                "num_env_steps_sampled": self._env_steps,
                "num_healthy_workers":
                    self.env_runner_group.num_healthy_actors(),
            },
            "learners": {"default_policy": metrics},
        }


@dataclasses.dataclass
class SACConfig(AlgorithmConfig):
    env: Any = "Pendulum-v1"
    lr: float = 3e-4                      # actor
    record_next_obs: bool = True   # off-policy TD needs true successors
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    tau: float = 0.005
    replay_capacity: int = 100_000
    train_batch_size: int = 256
    learning_starts: int = 1_000
    updates_per_env_step: float = 1.0
    rollout_fragment_length: int = 128
    algo_class = SAC
