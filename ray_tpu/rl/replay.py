"""Replay utilities shared by the off-policy algorithms (DQN, SAC) and
the sequence learners (DreamerV3, recurrent policies).

Reference: ``rllib/utils/replay_buffers/`` (buffer), the
episode-to-transition conversion the reference does in its off-policy
learner connector pipelines, and DreamerV3's episodic sequence replay.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np


def transitions_from_fragment(frag: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Rollout fragment → replayable transitions for off-policy TD.

    Runners record the TRUE successor state per step (``next_obs``,
    pre-reset at episode boundaries) and a ``terminated`` flag distinct
    from time-limit truncation — so the TD target bootstraps through
    truncations from the real final state (gym distinction the reference
    preserves; a truncated Pendulum episode still has future cost) and is
    cut only at genuine terminations. Fallback for externally produced
    fragments without those keys: shift obs for next_obs and drop the
    fragment's (next-obs-less) tail — never fabricate a self-transition."""
    obs = np.asarray(frag["obs"])
    if "next_obs" in frag:
        dones = np.asarray(frag.get("terminated", frag["dones"]),
                           dtype=np.float32)
        return {"obs": obs,
                "actions": np.asarray(frag["actions"]),
                "rewards": np.asarray(frag["rewards"], dtype=np.float32),
                "next_obs": np.asarray(frag["next_obs"]),
                "dones": dones}
    dones = np.asarray(frag["dones"], dtype=np.float32)
    return {"obs": obs[:-1],
            "actions": np.asarray(frag["actions"])[:-1],
            "rewards": np.asarray(frag["rewards"], dtype=np.float32)[:-1],
            "next_obs": obs[1:],
            "dones": dones[:-1]}


class ReplayBuffer:
    """Uniform ring replay of transitions (numpy, host-side).
    Reference: ``rllib/utils/replay_buffers/``."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._rng = np.random.default_rng(seed)
        self._storage: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def add_fragment(self, frag: Dict[str, np.ndarray]) -> None:
        """Append a rollout fragment of transitions (obs, actions,
        rewards, next_obs, dones)."""
        n = len(frag["obs"])
        if not self._storage:
            for k in ("obs", "actions", "rewards", "next_obs", "dones"):
                v = np.asarray(frag[k])
                self._storage[k] = np.zeros((self.capacity,) + v.shape[1:],
                                            dtype=v.dtype)
        for k, buf in self._storage.items():
            v = np.asarray(frag[k])
            idx = (self._next + np.arange(n)) % self.capacity
            buf[idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self.capacity, self._size + n)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: buf[idx] for k, buf in self._storage.items()}


class SequenceReplay:
    """Fragment-preserving replay sampling (B, L) windows with is_first
    markers (reference: DreamerV3's episodic replay).

    Stateful-module support (rl/module.py contract): fragments produced
    by env runners carry per-step ``state_in`` columns (the recurrent
    state the policy actually acted with) and true ``is_first`` flags.
    Both are stored, and :meth:`sample` ships each window's recorded
    state AT THE WINDOW START as flat ``state_in_<k>`` columns — the
    learner injects it into its scan instead of burning in from zeros,
    and mid-window resets replay from the flags. Fragments without
    recorded state (externally produced) still work: no state columns
    are emitted and learners fall back to zero initial state."""

    _BASE = ("obs", "actions", "rewards", "terminated", "is_first")

    def __init__(self, capacity_steps: int, seq_len: int, seed: int = 0):
        self._frags: List[Dict[str, np.ndarray]] = []
        self._steps = 0
        self._cap = capacity_steps
        self._L = seq_len
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return self._steps

    def add_fragment(self, frag: Dict[str, Any]) -> None:
        n = len(frag["obs"])
        if n < 2:
            return
        keep = {
            "obs": np.asarray(frag["obs"], np.float32),
            "actions": np.asarray(frag["actions"]),
            "rewards": np.asarray(frag["rewards"], np.float32),
            "terminated": np.asarray(
                frag.get("terminated", frag["dones"]), np.float32),
        }
        if "is_first" in frag:
            # runner-recorded flags: a fragment starting mid-episode stays
            # False at index 0, so windows resume from the injected state
            # instead of fabricating an episode boundary
            keep["is_first"] = np.asarray(frag["is_first"], bool).copy()
        else:
            # episode starts inside the fragment: step AFTER a done
            dones = np.asarray(frag["dones"], bool)
            keep["is_first"] = np.zeros(n, bool)
            keep["is_first"][0] = True
            keep["is_first"][1:] |= dones[:-1]
        for k, v in (frag.get("state_in") or {}).items():
            keep["state_in_" + k] = np.asarray(v, np.float32)
        self._frags.append(keep)
        self._steps += n
        while self._steps - len(self._frags[0]["obs"]) >= self._cap \
                and len(self._frags) > 1:
            self._steps -= len(self._frags.pop(0)["obs"])

    def _state_keys(self) -> List[str]:
        """State columns present in EVERY stored fragment (mixed buffers
        would otherwise produce ragged batches)."""
        if not self._frags:
            return []
        return [k for k in self._frags[0]
                if k.startswith("state_in_")
                and all(k in f for f in self._frags)]

    def sample(self, batch: int) -> Dict[str, np.ndarray]:
        L = self._L
        state_keys = self._state_keys()
        cols: Dict[str, List[np.ndarray]] = {
            k: [] for k in self._BASE + tuple(state_keys)}
        sizes = np.array([len(f["obs"]) for f in self._frags])
        ok = np.flatnonzero(sizes >= L)
        probs = sizes[ok] / sizes[ok].sum()
        for _ in range(batch):
            f = self._frags[ok[self._rng.choice(len(ok), p=probs)]]
            n = len(f["obs"])
            s = int(self._rng.integers(0, n - L + 1))
            for k in self._BASE:
                cols[k].append(f[k][s:s + L])
            for k in state_keys:      # flat state at the window start
                cols[k].append(f[k][s])
        return {k: np.stack(v) for k, v in cols.items()}

    def has_sequences(self, batch: int) -> bool:
        return any(len(f["obs"]) >= self._L for f in self._frags) \
            and self._steps >= batch * self._L
