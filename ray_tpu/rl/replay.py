"""Replay utilities shared by the off-policy algorithms (DQN, SAC).

Reference: ``rllib/utils/replay_buffers/`` (buffer) and the
episode-to-transition conversion the reference does in its off-policy
learner connector pipelines.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def transitions_from_fragment(frag: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Rollout fragment → replayable transitions for off-policy TD.

    Runners record the TRUE successor state per step (``next_obs``,
    pre-reset at episode boundaries) and a ``terminated`` flag distinct
    from time-limit truncation — so the TD target bootstraps through
    truncations from the real final state (gym distinction the reference
    preserves; a truncated Pendulum episode still has future cost) and is
    cut only at genuine terminations. Fallback for externally produced
    fragments without those keys: shift obs for next_obs and drop the
    fragment's (next-obs-less) tail — never fabricate a self-transition."""
    obs = np.asarray(frag["obs"])
    if "next_obs" in frag:
        dones = np.asarray(frag.get("terminated", frag["dones"]),
                           dtype=np.float32)
        return {"obs": obs,
                "actions": np.asarray(frag["actions"]),
                "rewards": np.asarray(frag["rewards"], dtype=np.float32),
                "next_obs": np.asarray(frag["next_obs"]),
                "dones": dones}
    dones = np.asarray(frag["dones"], dtype=np.float32)
    return {"obs": obs[:-1],
            "actions": np.asarray(frag["actions"])[:-1],
            "rewards": np.asarray(frag["rewards"], dtype=np.float32)[:-1],
            "next_obs": obs[1:],
            "dones": dones[:-1]}


class ReplayBuffer:
    """Uniform ring replay of transitions (numpy, host-side).
    Reference: ``rllib/utils/replay_buffers/``."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._rng = np.random.default_rng(seed)
        self._storage: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def add_fragment(self, frag: Dict[str, np.ndarray]) -> None:
        """Append a rollout fragment of transitions (obs, actions,
        rewards, next_obs, dones)."""
        n = len(frag["obs"])
        if not self._storage:
            for k in ("obs", "actions", "rewards", "next_obs", "dones"):
                v = np.asarray(frag[k])
                self._storage[k] = np.zeros((self.capacity,) + v.shape[1:],
                                            dtype=v.dtype)
        for k, buf in self._storage.items():
            v = np.asarray(frag[k])
            idx = (self._next + np.arange(n)) % self.capacity
            buf[idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self.capacity, self._size + n)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: buf[idx] for k, buf in self._storage.items()}
