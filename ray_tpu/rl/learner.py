"""Learner: JAX SGD step (reference ``rllib/core/learner/learner.py:107``).

The reference Learner wraps torch DDP; here the update is a pure jitted
function — on a TPU learner the same code pjit-s over a mesh (batch axis
data-parallel) with zero wiring, and multi-learner groups allreduce
through the collective library instead of NCCL.

Stateful modules: when the param pytree carries a recurrent family
marker (rl/module.py), PPO batches arrive as (B, L) sequence windows
with injected window-start state (``state_in_*``) and ``is_first``
flags; the whole window unrolls under ONE jitted ``lax.scan`` (no
python per-step work) and the clipped-surrogate loss is taken over the
flattened (B·L) steps. Minibatching permutes WHOLE windows so state
injection stays aligned with its window.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ray_tpu.rl.module import jax_forward, jax_lstm_forward_seq


class PPOLearner:
    """Clipped-surrogate PPO update (reference ``rllib/algorithms/ppo/``)."""

    def __init__(self, params: Dict[str, np.ndarray], *,
                 lr: float = 3e-4, clip: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, num_epochs: int = 4,
                 minibatch_size: int = 128, grad_clip: float = 0.5,
                 seed: int = 0):
        import jax
        import optax

        self.clip = clip
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.num_epochs = num_epochs
        self.minibatch_size = minibatch_size
        self._rng = np.random.default_rng(seed)

        self._optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip), optax.adam(lr))
        self._params = jax.tree.map(jax.numpy.asarray, dict(params))
        self._opt_state = self._optimizer.init(self._params)
        self._step = self._build_step()

    def _build_step(self):
        import jax
        import jax.numpy as jnp

        clip, vf_c, ent_c = self.clip, self.vf_coeff, self.entropy_coeff
        optimizer = self._optimizer

        def loss_fn(params, batch):
            if "lstm_wx" in params:
                # sequence window batch: (B, L, ...) + window-start state.
                # ONE scan over L, then the standard surrogate on the
                # flattened steps.
                state = {k[len("state_in_"):]: v
                         for k, v in batch.items()
                         if k.startswith("state_in_")}
                logits, values = jax_lstm_forward_seq(
                    params, batch["obs"], state, batch["is_first"])
                logits = logits.reshape(-1, logits.shape[-1])
                values = values.reshape(-1)
                actions = batch["actions"].reshape(-1)
                logp_old = batch["logp_old"].reshape(-1)
                adv = batch["advantages"].reshape(-1)
                value_targets = batch["value_targets"].reshape(-1)
            else:
                logits, values = jax_forward(params, batch["obs"])
                actions = batch["actions"]
                logp_old = batch["logp_old"]
                adv = batch["advantages"]
                value_targets = batch["value_targets"]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, actions[:, None].astype(jnp.int32),
                axis=1)[:, 0]
            ratio = jnp.exp(logp - logp_old)
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
            pi_loss = -surr.mean()
            vf_loss = jnp.mean((values - value_targets) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
            total = pi_loss + vf_c * vf_loss - ent_c * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "clip_frac": jnp.mean(
                               (jnp.abs(ratio - 1.0) > clip).astype(
                                   jnp.float32))}

        def step(params, opt_state, batch):
            (total, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            aux["total_loss"] = total
            return params, opt_state, aux

        # Split grad/apply pair for multi-learner groups (reference Learner
        # API: compute_gradients:464 / apply_gradients:607) — the allreduce
        # slots between the two jitted calls.
        def grad(params, batch):
            (total, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            aux["total_loss"] = total
            return grads, aux

        def apply(params, opt_state, grads):
            import optax

            updates, opt_state = optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._grad_fn = jax.jit(grad)
        self._apply_fn = jax.jit(apply, donate_argnums=(0, 1))

        return jax.jit(step, donate_argnums=(0, 1))

    # ------------------------------------------------------------- update
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """Minibatched multi-epoch PPO update. Batch keys: obs, actions,
        logp_old, advantages, value_targets — flat (N, ...) for
        feedforward modules, (B, L, ...) windows + state_in_*/is_first
        for stateful ones (minibatches then index whole windows, and
        minibatch_size counts STEPS, so B·L per minibatch stays
        comparable across module families)."""
        import jax.numpy as jnp

        from ray_tpu.rl.module import get_initial_state, is_stateful

        n = len(batch["obs"])
        mb_size = self.minibatch_size
        if is_stateful(self._params):
            if "lstm_wx" not in self._params:
                raise ValueError(
                    "PPOLearner supports the LSTM stateful family only; "
                    "RSSM acting towers are inference-only exports "
                    "(trained by DreamerV3Learner), not PPO-trainable")
            # loss_fn branches on the PARAMS marker, so the batch must be
            # sequence-shaped — fail loudly here rather than with a
            # KeyError inside the jitted step
            obs = np.asarray(batch["obs"])
            if obs.ndim != 3:
                raise ValueError(
                    "stateful module requires a (B, L, obs) sequence "
                    f"batch (see window_sequences); got shape {obs.shape}")
            # zero-state fallback for externally built windows without
            # recorded acting state (mirrors DreamerV3Learner.update)
            batch = dict(batch)
            if "is_first" not in batch:
                first = np.zeros(obs.shape[:2], bool)
                first[:, 0] = True
                batch["is_first"] = first
            for k, v in get_initial_state(self._params, n).items():
                batch.setdefault("state_in_" + k, np.asarray(v))
            mb_size = max(1, self.minibatch_size // obs.shape[1])
        metrics = {}
        for _ in range(self.num_epochs):
            perm = self._rng.permutation(n)
            for lo in range(0, n, mb_size):
                idx = perm[lo:lo + mb_size]
                mb = {k: jnp.asarray(v[idx]) for k, v in batch.items()}
                self._params, self._opt_state, aux = self._step(
                    self._params, self._opt_state, mb)
            metrics = {k: float(v) for k, v in aux.items()}
        return metrics

    # --------------------------------------------- multi-learner grad split
    def compute_gradients(self, batch: Dict[str, np.ndarray]):
        """Gradients on this shard WITHOUT applying them; pair with
        :meth:`apply_gradients` around an allreduce (LearnerGroup)."""
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k != "episode_returns"}
        grads, aux = self._grad_fn(self._params, jb)
        return grads, aux

    def apply_gradients(self, grads) -> None:
        self._params, self._opt_state = self._apply_fn(
            self._params, self._opt_state, grads)
        self.updates = getattr(self, "updates", 0) + 1

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self._params.items()}

    def set_weights(self, params: Dict[str, np.ndarray]):
        import jax

        self._params = jax.tree.map(jax.numpy.asarray, dict(params))


def build_ppo_batch(fragments, *, gamma: float = 0.99, lam: float = 0.95,
                    seq_len: int = None):
    """Assemble the PPO train batch from rollout fragments: per-fragment
    GAE, column stacking ((F, T, ...) + ``window_sequences`` for
    stateful modules, flat concatenation otherwise), and advantage
    normalization.  ONE implementation shared by ``PPO.training_step``
    and the Podracer Sebulba learner actor, so the asynchronous path
    trains on byte-identical batches to the synchronous parity oracle.

    Returns ``(batch, episode_returns, env_steps)``.
    """
    advs, targets, returns = [], [], []
    for f in fragments:
        a, vt = compute_gae(
            f["rewards"], f["values"], f["dones"], f["last_value"],
            gamma=gamma, lam=lam)
        advs.append(a)
        targets.append(vt)
        returns.extend(f["episode_returns"])
    stateful = "state_in" in fragments[0]
    if stateful:
        # keep time structure: (F, T, ...) columns, GAE per fragment as
        # above, then cut into (B, L) windows with the recorded state at
        # window starts (burn-in-free injection)
        batch = {
            "obs": np.stack([f["obs"] for f in fragments]),
            "actions": np.stack([f["actions"] for f in fragments]),
            "logp_old": np.stack([f["logp"] for f in fragments]),
            "advantages": np.stack(advs),
            "value_targets": np.stack(targets),
            "is_first": np.stack([f["is_first"] for f in fragments]),
        }
        for k in fragments[0]["state_in"]:
            batch["state_in_" + k] = np.stack(
                [f["state_in"][k] for f in fragments])
    else:
        batch = {
            "obs": np.concatenate([f["obs"] for f in fragments]),
            "actions": np.concatenate([f["actions"] for f in fragments]),
            "logp_old": np.concatenate([f["logp"] for f in fragments]),
            "advantages": np.concatenate(advs),
            "value_targets": np.concatenate(targets),
        }
    adv = batch["advantages"]
    batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
    if stateful:
        from ray_tpu.rl.connectors import window_sequences

        if seq_len is None:
            raise ValueError("stateful fragments need seq_len")
        batch = window_sequences(batch, seq_len)
    env_steps = sum(len(f["obs"]) for f in fragments)
    return batch, returns, env_steps


def compute_gae(rewards, values, dones, last_value, *,
                gamma: float = 0.99, lam: float = 0.95
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Generalized advantage estimation over a rollout fragment."""
    T = len(rewards)
    adv = np.zeros(T, np.float32)
    next_value = last_value
    gae = 0.0
    # dones[t] == episode ended AT step t → no bootstrap/propagation across
    # the t → t+1 boundary.
    for t in range(T - 1, -1, -1):
        nonterminal = 0.0 if dones[t] else 1.0
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        gae = delta + gamma * lam * nonterminal * gae
        adv[t] = gae
        next_value = values[t]
    value_targets = adv + values
    return adv, value_targets
