"""Learner: JAX SGD step (reference ``rllib/core/learner/learner.py:107``).

The reference Learner wraps torch DDP; here the update is a pure jitted
function — on a TPU learner the same code pjit-s over a mesh (batch axis
data-parallel) with zero wiring, and multi-learner groups allreduce
through the collective library instead of NCCL.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ray_tpu.rl.module import jax_forward


class PPOLearner:
    """Clipped-surrogate PPO update (reference ``rllib/algorithms/ppo/``)."""

    def __init__(self, params: Dict[str, np.ndarray], *,
                 lr: float = 3e-4, clip: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, num_epochs: int = 4,
                 minibatch_size: int = 128, grad_clip: float = 0.5,
                 seed: int = 0):
        import jax
        import optax

        self.clip = clip
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.num_epochs = num_epochs
        self.minibatch_size = minibatch_size
        self._rng = np.random.default_rng(seed)

        self._optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip), optax.adam(lr))
        self._params = jax.tree.map(jax.numpy.asarray, dict(params))
        self._opt_state = self._optimizer.init(self._params)
        self._step = self._build_step()

    def _build_step(self):
        import jax
        import jax.numpy as jnp

        clip, vf_c, ent_c = self.clip, self.vf_coeff, self.entropy_coeff
        optimizer = self._optimizer

        def loss_fn(params, batch):
            logits, values = jax_forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None].astype(jnp.int32),
                axis=1)[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
            pi_loss = -surr.mean()
            vf_loss = jnp.mean((values - batch["value_targets"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
            total = pi_loss + vf_c * vf_loss - ent_c * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "clip_frac": jnp.mean(
                               (jnp.abs(ratio - 1.0) > clip).astype(
                                   jnp.float32))}

        def step(params, opt_state, batch):
            (total, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            aux["total_loss"] = total
            return params, opt_state, aux

        # Split grad/apply pair for multi-learner groups (reference Learner
        # API: compute_gradients:464 / apply_gradients:607) — the allreduce
        # slots between the two jitted calls.
        def grad(params, batch):
            (total, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            aux["total_loss"] = total
            return grads, aux

        def apply(params, opt_state, grads):
            import optax

            updates, opt_state = optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._grad_fn = jax.jit(grad)
        self._apply_fn = jax.jit(apply, donate_argnums=(0, 1))

        return jax.jit(step, donate_argnums=(0, 1))

    # ------------------------------------------------------------- update
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """Minibatched multi-epoch PPO update. Batch keys: obs, actions,
        logp_old, advantages, value_targets."""
        import jax.numpy as jnp

        n = len(batch["obs"])
        metrics = {}
        for _ in range(self.num_epochs):
            perm = self._rng.permutation(n)
            for lo in range(0, n, self.minibatch_size):
                idx = perm[lo:lo + self.minibatch_size]
                mb = {k: jnp.asarray(v[idx]) for k, v in batch.items()}
                self._params, self._opt_state, aux = self._step(
                    self._params, self._opt_state, mb)
            metrics = {k: float(v) for k, v in aux.items()}
        return metrics

    # --------------------------------------------- multi-learner grad split
    def compute_gradients(self, batch: Dict[str, np.ndarray]):
        """Gradients on this shard WITHOUT applying them; pair with
        :meth:`apply_gradients` around an allreduce (LearnerGroup)."""
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k != "episode_returns"}
        grads, aux = self._grad_fn(self._params, jb)
        return grads, aux

    def apply_gradients(self, grads) -> None:
        self._params, self._opt_state = self._apply_fn(
            self._params, self._opt_state, grads)
        self.updates = getattr(self, "updates", 0) + 1

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self._params.items()}

    def set_weights(self, params: Dict[str, np.ndarray]):
        import jax

        self._params = jax.tree.map(jax.numpy.asarray, dict(params))


def compute_gae(rewards, values, dones, last_value, *,
                gamma: float = 0.99, lam: float = 0.95
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Generalized advantage estimation over a rollout fragment."""
    T = len(rewards)
    adv = np.zeros(T, np.float32)
    next_value = last_value
    gae = 0.0
    # dones[t] == episode ended AT step t → no bootstrap/propagation across
    # the t → t+1 boundary.
    for t in range(T - 1, -1, -1):
        nonterminal = 0.0 if dones[t] else 1.0
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        gae = delta + gamma * lam * nonterminal * gae
        adv[t] = gae
        next_value = values[t]
    value_targets = adv + values
    return adv, value_targets
