"""Offline RL: experience datasets on disk + behavior cloning.

Reference: ``rllib/offline/`` (JsonWriter/JsonReader sample IO,
``rllib/algorithms/bc/bc.py`` behavior cloning on logged actions). Data
interop: fragments written by env runners load back as column arrays, and
``to_dataset`` bridges into ray_tpu.data for pipeline-style transforms.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np


class JsonWriter:
    """Append rollout fragments as JSONL (one fragment per line)."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        self._dir = path
        os.makedirs(path, exist_ok=True)
        self._max = max_file_size
        self._index = 0
        self._file = None

    def _rotate(self):
        if self._file is not None:
            self._file.close()
        name = os.path.join(self._dir, f"output-{self._index:05d}.jsonl")
        self._index += 1
        self._file = open(name, "a")

    def write(self, fragment: Dict[str, Any]) -> None:
        if self._file is None or self._file.tell() > self._max:
            self._rotate()
        row = {}
        for k, v in fragment.items():
            row[k] = v.tolist() if isinstance(v, np.ndarray) else v
        self._file.write(json.dumps(row) + "\n")
        self._file.flush()

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None


class JsonReader:
    """Read fragments back as numpy column dicts."""

    # actions: None = infer (int32 for discrete logs, float32 continuous)
    _ARRAY_DTYPES = {"obs": np.float32, "actions": None,
                     "rewards": np.float32, "dones": np.bool_,
                     "terminated": np.bool_, "next_obs": np.float32,
                     "logp": np.float32, "values": np.float32}

    def __init__(self, path: str):
        self._files = sorted(glob.glob(os.path.join(path, "*.jsonl"))) \
            if os.path.isdir(path) else [path]
        if not self._files:
            raise FileNotFoundError(f"no .jsonl files under {path}")

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        for fn in self._files:
            with open(fn) as f:
                for line in f:
                    if not line.strip():
                        continue
                    row = json.loads(line)
                    for k, dt in self._ARRAY_DTYPES.items():
                        if k not in row:
                            continue
                        if dt is None:
                            arr = np.asarray(row[k])
                            dt = (np.int32 if arr.dtype.kind in "iub"
                                  else np.float32)
                            row[k] = arr.astype(dt)
                        else:
                            row[k] = np.asarray(row[k], dt)
                    yield row

    def read_all(self) -> Dict[str, np.ndarray]:
        cols: Dict[str, List[np.ndarray]] = {}
        for row in self:
            for k, v in row.items():
                if isinstance(v, np.ndarray):
                    cols.setdefault(k, []).append(v)
        return {k: np.concatenate(v) for k, v in cols.items()}


def to_dataset(path: str):
    """Bridge into ray_tpu.data: one block row per transition."""
    from ray_tpu import data

    cols = JsonReader(path).read_all()
    n = len(cols["actions"])
    return data.from_items([
        {k: cols[k][i].tolist() if cols[k][i].ndim else cols[k][i].item()
         for k in cols} for i in range(n)
    ])


def collect(env_spec, policy_params, path: str, *, num_steps: int = 2048,
            seed: int = 0, record_next_obs: bool = False) -> str:
    """Roll out a policy and persist the experience (reference
    ``rllib ... output`` config): the offline-data entry point.
    ``record_next_obs`` persists true successors + the terminated flag —
    what offline TD consumers (CQL) need."""
    from ray_tpu.rl.env_runner import EnvRunner

    runner = EnvRunner(env_spec, seed=seed, record_next_obs=record_next_obs)
    runner.set_weights(policy_params)
    writer = JsonWriter(path)
    wrote = 0
    while wrote < num_steps:
        frag = runner.sample(min(512, num_steps - wrote))
        writer.write({k: v for k, v in frag.items()
                      if k in JsonReader._ARRAY_DTYPES})
        wrote += len(frag["actions"])
    writer.close()
    return path


@dataclasses.dataclass
class BCConfig:
    input_path: str = ""
    lr: float = 1e-3
    num_epochs: int = 1
    minibatch_size: int = 256
    hidden: tuple = (64, 64)
    seed: int = 0
    env: Union[str, Any] = "CartPole-v1"  # only needed for evaluate()

    def build(self) -> "BC":
        return BC(self)


class BC:
    """Behavior cloning: maximize log π(a_logged | s) over the dataset
    (reference ``rllib/algorithms/bc``). The simplest offline algorithm —
    and the correctness anchor for the offline data path."""

    def __init__(self, config: BCConfig):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rl.module import init_policy_params, jax_forward

        self.config = config
        data = JsonReader(config.input_path).read_all()
        self._obs = np.asarray(data["obs"], np.float32)
        self._actions = np.asarray(data["actions"], np.int32)
        self.params = init_policy_params(
            self._obs.shape[-1], int(self._actions.max()) + 1,
            hidden=tuple(config.hidden), seed=config.seed)
        self._opt = optax.adam(config.lr)
        self._opt_state = self._opt.init(self.params)
        self.iteration = 0

        def loss(params, obs, actions):
            logits, _ = jax_forward(params, obs)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, actions[:, None].astype(jnp.int32), axis=-1)
            return nll.mean()

        @jax.jit
        def step(params, opt_state, obs, actions):
            l, g = jax.value_and_grad(loss)(params, obs, actions)
            updates, opt_state = self._opt.update(g, opt_state, params)
            import optax as _optax

            return _optax.apply_updates(params, updates), opt_state, l

        self._step = step
        self._rng = np.random.default_rng(config.seed)

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        n = len(self._obs)
        mb = min(self.config.minibatch_size, n)
        losses = []
        for _ in range(self.config.num_epochs):
            order = self._rng.permutation(n)
            for i in range(0, n - mb + 1, mb):
                idx = order[i:i + mb]
                self.params, self._opt_state, l = self._step(
                    self.params, self._opt_state, self._obs[idx],
                    self._actions[idx])
                losses.append(float(l))
        return {"training_iteration": self.iteration,
                "bc_loss": float(np.mean(losses))}

    def evaluate(self, num_episodes: int = 5,
                 seed: int = 100) -> Dict[str, float]:
        from ray_tpu.rl.envs import make_env
        from ray_tpu.rl.module import np_forward

        env = make_env(self.config.env, seed=seed)
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed + ep)
            total, done = 0.0, False
            while not done:
                logits, _ = np_forward(
                    jax_to_np(self.params), np.asarray(obs)[None])
                obs, r, term, trunc, _ = env.step(int(logits[0].argmax()))
                total += r
                done = term or trunc
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns))}


def jax_to_np(params):
    return {k: np.asarray(v) for k, v in params.items()}
