"""Multi-learner LearnerGroup with synchronized gradients.

Reference: ``rllib/core/learner/learner_group.py:100`` — N learner actors
wrapped in torch DDP with an async update queue. TPU-first redesign: the
learners are plain actors holding jitted JAX learners; gradient sync is a
per-leaf allreduce through ``ray_tpu.collective`` (KV backend on CPU hosts,
XLA/ICI backend on TPU meshes) between ``compute_gradients`` and
``apply_gradients`` — the same split the reference Learner API exposes
(``learner.py:464 compute_gradients``, ``:607 apply_gradients``).

Synchronization model: every ``update`` shards one batch across all N
learners and each applies the *mean* gradient, so parameters stay bitwise
in sync (same init, same averaged grads, same optimizer). ``async_update``
pipelines batches through the actors' ordered submission queues — rank
lockstep is preserved because every actor processes update k before k+1.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


def _num_steps(batch) -> int:
    """Env steps in a train batch: B for flat batches, B·L for (B, L)
    sequence windows of a stateful module (rl/module.py contract).
    Sequence windows are identified by their marker columns, NOT by obs
    rank — a flat batch of image observations is also ndim >= 3."""
    if hasattr(batch, "get") and batch.get("obs") is not None and (
            "is_first" in batch
            or any(str(k).startswith("state_in_") for k in batch)):
        obs = np.asarray(batch["obs"])
        if obs.ndim >= 3:
            return int(obs.shape[0] * obs.shape[1])
    return len(next(iter(batch.values())))


class LearnerWorker:
    """One learner actor: local jitted learner + collective gradient sync."""

    def __init__(self, factory_blob: bytes, rank: int, world_size: int,
                 group_name: str, backend: str = "kv"):
        import cloudpickle

        factory = cloudpickle.loads(factory_blob)
        self._learner = factory()
        self._rank = rank
        self._world = world_size
        self._group = group_name
        self._backend = backend
        self._group_ready = False

    def ping(self) -> bool:
        return True

    def _ensure_group(self):
        """Join the collective group lazily, on the FIRST update: the GCS
        serializes actor creations, so a rendezvous inside the constructor
        would deadlock rank 0 against rank 1's unstarted creation. First
        updates are submitted to all ranks concurrently, so all members
        arrive here together."""
        if self._group_ready or self._world == 1:
            return
        from ray_tpu import collective

        collective.init_collective_group(
            self._world, self._rank, backend=self._backend,
            group_name=self._group)
        self._group_ready = True

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One synchronized SGD step on this rank's shard."""
        if self._world == 1:
            return self._learner.update(batch)
        from ray_tpu import collective

        self._ensure_group()
        grads, aux = self._learner.compute_gradients(batch)
        import jax

        leaves, treedef = jax.tree.flatten(grads)
        # mean-allreduce each leaf: SUM over ranks, then / world — learners
        # stay identical because every rank applies the same averaged grad
        reduced = [
            np.asarray(collective.allreduce(
                np.asarray(leaf, np.float32), group_name=self._group))
            / self._world
            for leaf in leaves
        ]
        self._learner.apply_gradients(jax.tree.unflatten(treedef, reduced))
        out = {k: float(v) for k, v in aux.items()}
        out["num_env_steps_trained"] = _num_steps(batch)
        return out

    def get_weights(self) -> Dict[str, np.ndarray]:
        return self._learner.get_weights()

    def set_weights(self, weights: Dict[str, np.ndarray]) -> None:
        self._learner.set_weights(weights)

    def num_updates(self) -> int:
        return getattr(self._learner, "updates", 0)


class LearnerGroup:
    """Fan-out controller over N learner actors (reference
    ``LearnerGroup``). ``update`` is synchronous; ``async_update`` pipelines
    through the actors' ordered queues and ``poll_updates`` drains finished
    results — the IMPALA-family consumption pattern."""

    def __init__(self, learner_factory: Callable[[], Any], *,
                 num_learners: int = 1, backend: str = "kv",
                 group_name: Optional[str] = None,
                 ray_remote_args: Optional[dict] = None,
                 max_inflight_updates: int = 4):
        import os

        import cloudpickle

        import ray_tpu

        self._n = max(1, num_learners)
        self._group_name = group_name or f"learner_group_{os.getpid()}_{id(self)}"
        self._max_inflight = max_inflight_updates
        blob = cloudpickle.dumps(learner_factory)
        cls = ray_tpu.remote(LearnerWorker)
        opts = dict(ray_remote_args or {})
        opts.setdefault("num_cpus", 0)
        self._workers = [
            cls.options(**opts).remote(blob, rank, self._n,
                                       self._group_name, backend)
            for rank in range(self._n)
        ]
        # Constructors run concurrently; the collective group rendezvous
        # inside them completes only when all ranks arrive.
        ray_tpu.get([w.ping.remote() for w in self._workers], timeout=120)
        self._inflight: List[List[Any]] = []  # list of per-rank ref lists

    # -------------------------------------------------------------- helpers
    @staticmethod
    def _shard(batch: Dict[str, np.ndarray], n: int
               ) -> List[Dict[str, np.ndarray]]:
        """Slice along axis 0. For (B, L) sequence batches this is
        sequence-aware by construction: whole windows move together, so
        every rank's ``state_in_*`` rows stay aligned with their
        windows."""
        if n == 1:
            return [batch]
        size = len(next(iter(batch.values())))
        per = size // n
        if per == 0:
            return [batch] * n  # degenerate tiny batch: replicate
        shards = []
        for i in range(n):
            lo = i * per
            hi = size if i == n - 1 else (i + 1) * per
            shards.append({k: v[lo:hi] for k, v in batch.items()})
        return shards

    @staticmethod
    def _merge(metrics: List[Dict[str, float]]) -> Dict[str, float]:
        out: Dict[str, float] = {}
        if not metrics:
            return out
        for k in metrics[0]:
            vals = [m[k] for m in metrics if k in m]
            out[k] = (float(np.sum(vals)) if k.startswith("num_")
                      else float(np.mean(vals)))
        return out

    # --------------------------------------------------------------- update
    def update(self, batch: Dict[str, np.ndarray],
               timeout: float = 300.0) -> Dict[str, float]:
        import ray_tpu

        shards = self._shard(batch, self._n)
        refs = [w.update.remote(s) for w, s in zip(self._workers, shards)]
        return self._merge(ray_tpu.get(refs, timeout=timeout))

    def async_update(self, batch: Dict[str, np.ndarray]) -> bool:
        """Enqueue one synchronized update without waiting. Returns False
        (and drops the batch) when the pipeline is full — IMPALA-style
        backpressure on the learner queue."""
        if len(self._inflight) >= self._max_inflight:
            return False
        shards = self._shard(batch, self._n)
        self._inflight.append(
            [w.update.remote(s) for w, s in zip(self._workers, shards)])
        return True

    def poll_updates(self, timeout: float = 0.0) -> List[Dict[str, float]]:
        """Drain finished async updates (oldest first, order preserved)."""
        import ray_tpu

        done: List[Dict[str, float]] = []
        while self._inflight:
            refs = self._inflight[0]
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                    timeout=timeout)
            if len(ready) < len(refs):
                break
            self._inflight.pop(0)
            done.append(self._merge(ray_tpu.get(refs)))
        return done

    @property
    def num_inflight_updates(self) -> int:
        return len(self._inflight)

    # -------------------------------------------------------------- weights
    def get_weights(self, timeout: float = 60.0) -> Dict[str, np.ndarray]:
        import ray_tpu

        return ray_tpu.get(self._workers[0].get_weights.remote(),
                           timeout=timeout)

    def set_weights(self, weights: Dict[str, np.ndarray]) -> None:
        import ray_tpu

        ray_tpu.get([w.set_weights.remote(weights) for w in self._workers],
                    timeout=60)

    def num_updates(self, timeout: float = 60.0) -> int:
        import ray_tpu

        return ray_tpu.get(self._workers[0].num_updates.remote(),
                           timeout=timeout)

    @property
    def num_learners(self) -> int:
        return self._n

    def shutdown(self):
        import ray_tpu

        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
