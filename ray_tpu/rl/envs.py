"""Built-in environments (gymnasium API shape: reset → (obs, info),
step → (obs, reward, terminated, truncated, info)).

The reference depends on external gym; this image has none, and rollout
workers shouldn't need an accelerator runtime anyway — these are pure
numpy. ``make_env`` also accepts any user callable returning an object
with the same API, so external gymnasium envs plug straight in.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Union

import numpy as np


class CartPoleEnv:
    """Classic cart-pole (dynamics per Barto-Sutton-Anderson / gym
    CartPole-v1: termination at |x|>2.4, |θ|>12°, truncation at 500)."""

    observation_size = 4
    num_actions = 2
    max_episode_steps = 500

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    X_LIMIT = 2.4
    THETA_LIMIT = 12 * np.pi / 180

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros(4)
        self._steps = 0

    def reset(self, seed: Optional[int] = None) -> Tuple[np.ndarray, Dict]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self._state.astype(np.float32).copy(), {}

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        temp = (force + pole_ml * theta_dot**2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LEN
            * (4.0 / 3.0 - self.POLE_MASS * cos_t**2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        self._state = np.array([
            x + self.DT * x_dot,
            x_dot + self.DT * x_acc,
            theta + self.DT * theta_dot,
            theta_dot + self.DT * theta_acc,
        ])
        self._steps += 1
        terminated = bool(abs(self._state[0]) > self.X_LIMIT
                          or abs(self._state[2]) > self.THETA_LIMIT)
        truncated = self._steps >= self.max_episode_steps
        return (self._state.astype(np.float32).copy(), 1.0, terminated,
                truncated, {})


class CartPoleMaskedVelocityEnv(CartPoleEnv):
    """CartPole POMDP: observations expose only the POSITIONS (x, θ) —
    velocities are masked. The standard memory benchmark for recurrent
    policies (Duan et al. '16 "masked-velocity" control suite): a
    feedforward policy cannot distinguish a pole swinging left from one
    swinging right through the upright, so it cannot stabilize; a
    stateful policy recovers the velocities from two consecutive
    observations. Initial VELOCITIES are drawn wider than stock CartPole
    so the hidden state genuinely varies and cannot be assumed zero."""

    observation_size = 2

    def _mask(self, obs: np.ndarray) -> np.ndarray:
        return obs[[0, 2]]

    def reset(self, seed: Optional[int] = None) -> Tuple[np.ndarray, Dict]:
        obs, info = super().reset(seed=seed)
        # re-draw velocities from a wider range (positions stay stock)
        self._state[1] = self._rng.uniform(-0.5, 0.5)
        self._state[3] = self._rng.uniform(-0.5, 0.5)
        return self._mask(self._state.astype(np.float32)), info

    def step(self, action: int):
        obs, reward, terminated, truncated, info = super().step(action)
        return self._mask(obs), reward, terminated, truncated, info


class PendulumEnv:
    """Classic underactuated pendulum swing-up (gym Pendulum-v1
    dynamics): obs (cosθ, sinθ, θ̇), one continuous torque in
    [-2, 2], reward -(θ² + 0.1·θ̇² + 0.001·a²), 200-step episodes.
    The stock continuous-control testbed for SAC-class algorithms
    (reference: rllib/tuned_examples/sac/pendulum_sac.py)."""

    observation_size = 3
    action_dim = 1                    # continuous: no num_actions
    action_low = -2.0
    action_high = 2.0
    max_episode_steps = 200

    GRAVITY = 10.0
    MASS = 1.0
    LENGTH = 1.0
    DT = 0.05
    MAX_SPEED = 8.0

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self._theta = 0.0
        self._theta_dot = 0.0
        self._steps = 0

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self._theta), np.sin(self._theta),
                         self._theta_dot], np.float32)

    def reset(self, seed: Optional[int] = None) -> Tuple[np.ndarray, Dict]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._theta = self._rng.uniform(-np.pi, np.pi)
        self._theta_dot = self._rng.uniform(-1.0, 1.0)
        self._steps = 0
        return self._obs(), {}

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          self.action_low, self.action_high))
        th, thdot = self._theta, self._theta_dot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + self.DT * (
            3 * self.GRAVITY / (2 * self.LENGTH) * np.sin(th)
            + 3.0 / (self.MASS * self.LENGTH ** 2) * u)
        thdot = float(np.clip(thdot, -self.MAX_SPEED, self.MAX_SPEED))
        th = th + self.DT * thdot
        self._theta, self._theta_dot = th, thdot
        self._steps += 1
        truncated = self._steps >= self.max_episode_steps
        return self._obs(), -float(cost), False, truncated, {}


class JaxCartPole:
    """Functional, batched, jittable CartPole for in-graph (Anakin)
    training: ``reset``/``step`` are pure functions over a state pytree,
    traceable under ``jax.jit``/``lax.scan``.  Dynamics, termination
    bounds, and the reset distribution mirror :class:`CartPoleEnv`
    exactly (tests/test_podracer.py pins numpy parity); ``step``
    auto-resets done envs in-graph (the returned obs is the NEXT policy
    input, so a fresh episode starts without leaving the compiled
    program).  jax imports stay inside methods — this module must stay
    importable in numpy-only rollout workers."""

    observation_size = 4
    num_actions = 2
    max_episode_steps = 500

    @staticmethod
    def reset(key, batch_size: int):
        """-> (state, obs): state {"s": (B, 4), "steps": (B,) int32}."""
        import jax
        import jax.numpy as jnp

        s = jax.random.uniform(key, (batch_size, 4),
                               minval=-0.05, maxval=0.05)
        return ({"s": s, "steps": jnp.zeros(batch_size, jnp.int32)},
                s.astype(jnp.float32))

    @staticmethod
    def physics(s, action):
        """One Euler step of the cart-pole dynamics, batched: ``s``
        (B, 4), ``action`` (B,) in {0, 1} -> next (B, 4).  Same
        equations, same constants as ``CartPoleEnv.step``."""
        import jax.numpy as jnp

        E = CartPoleEnv
        x, x_dot, theta, theta_dot = s[:, 0], s[:, 1], s[:, 2], s[:, 3]
        force = jnp.where(action == 1, E.FORCE, -E.FORCE)
        total_mass = E.CART_MASS + E.POLE_MASS
        pole_ml = E.POLE_MASS * E.POLE_HALF_LEN
        cos_t, sin_t = jnp.cos(theta), jnp.sin(theta)
        temp = (force + pole_ml * theta_dot**2 * sin_t) / total_mass
        theta_acc = (E.GRAVITY * sin_t - cos_t * temp) / (
            E.POLE_HALF_LEN
            * (4.0 / 3.0 - E.POLE_MASS * cos_t**2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        return jnp.stack([
            x + E.DT * x_dot,
            x_dot + E.DT * x_acc,
            theta + E.DT * theta_dot,
            theta_dot + E.DT * theta_acc,
        ], axis=1)

    @staticmethod
    def step(state, action, key):
        """-> (state', obs, reward, done); done envs are re-drawn from
        the reset distribution in-graph (their obs is the new episode's
        first observation)."""
        import jax
        import jax.numpy as jnp

        E = CartPoleEnv
        s2 = JaxCartPole.physics(state["s"], action)
        steps = state["steps"] + 1
        terminated = ((jnp.abs(s2[:, 0]) > E.X_LIMIT)
                      | (jnp.abs(s2[:, 2]) > E.THETA_LIMIT))
        truncated = steps >= JaxCartPole.max_episode_steps
        done = terminated | truncated
        fresh = jax.random.uniform(key, s2.shape, minval=-0.05,
                                   maxval=0.05)
        s_next = jnp.where(done[:, None], fresh, s2)
        steps = jnp.where(done, 0, steps)
        reward = jnp.ones(s2.shape[0], jnp.float32)
        return ({"s": s_next, "steps": steps},
                s_next.astype(jnp.float32), reward, done)


_JAX_REGISTRY: Dict[str, Any] = {
    "CartPole-v1": JaxCartPole,
}


def register_jax_env(name: str, env_cls: Any) -> None:
    """Register a functional in-graph env (JaxCartPole-shaped
    ``reset(key, batch)`` / ``step(state, action, key)``) for Anakin."""
    _JAX_REGISTRY[name] = env_cls


def get_jax_env(spec: Union[str, Any]):
    """Resolve an Anakin in-graph env: registered name, or any object
    already exposing the functional reset/step surface."""
    if isinstance(spec, str):
        if spec not in _JAX_REGISTRY:
            raise KeyError(
                f"no in-graph (jittable) env registered for {spec!r}; "
                "register one with register_jax_env() or use Sebulba "
                f"mode. Known: {sorted(_JAX_REGISTRY)}")
        return _JAX_REGISTRY[spec]
    if hasattr(spec, "reset") and hasattr(spec, "step"):
        return spec
    raise TypeError(f"{spec!r} is not an in-graph env")


def _coordination_factory(seed=None):
    from ray_tpu.rl.multi_agent import CoordinationGameEnv

    return CoordinationGameEnv(seed=seed)


_REGISTRY: Dict[str, Callable[..., Any]] = {
    "CartPole-v1": CartPoleEnv,
    "CartPoleMaskedVelocity-v1": CartPoleMaskedVelocityEnv,
    "Pendulum-v1": PendulumEnv,
    "coordination": _coordination_factory,
}


def register_env(name: str, factory: Callable[..., Any]) -> None:
    _REGISTRY[name] = factory


def make_env(spec: Union[str, Callable[..., Any]], seed: Optional[int] = None):
    factory = _REGISTRY[spec] if isinstance(spec, str) else spec
    try:
        return factory(seed=seed)
    except TypeError:
        return factory()
