"""CQL: conservative Q-learning for offline RL.

Reference: ``rllib/algorithms/cql/cql.py`` (CQL(H) on top of SAC: the
twin critics additionally minimize a conservative regularizer
``logsumexp_a Q(s, a) - Q(s, a_data)`` so out-of-distribution actions
cannot carry inflated values — the failure mode of running plain SAC on
a fixed dataset).

TPU framing: :class:`CQLLearner` is :class:`SACLearner` with the
``_conservative_penalty`` hook filled in — one jitted step; the OOD
action fan-out (N uniform + policy + next-policy samples per state) is a
single batched Q forward, so the penalty rides the MXU with the rest of
the update.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Union

import numpy as np

from ray_tpu.rl.replay import ReplayBuffer, transitions_from_fragment
from ray_tpu.rl.offline import JsonReader
from ray_tpu.rl.sac import SACLearner


class CQLLearner(SACLearner):
    def __init__(self, *args, cql_alpha: float = 1.0,
                 cql_n_actions: int = 4, **kwargs):
        self.cql_alpha = cql_alpha
        self.cql_n_actions = cql_n_actions
        super().__init__(*args, **kwargs)

    def _conservative_penalty(self, qs, actor, batch, key):
        """logsumexp over {uniform, pi(s), pi(s')} actions minus the
        dataset action's Q, per critic (CQL(H), ``cql.py`` cql_loss)."""
        import jax
        import jax.numpy as jnp

        p1, p2 = qs
        qf, sample = self._q_forward, self._sample_squashed
        obs = batch["obs"]
        n, d = obs.shape[0], self.action_dim
        scale = actor["action_scale"]
        k_rand, k_pi, k_pin = jax.random.split(key, 3)
        samples = []  # each: (q1_vals, q2_vals) of shape (n,)
        rand = jax.random.uniform(
            k_rand, (self.cql_n_actions, n, d),
            minval=-scale, maxval=scale)
        for i in range(self.cql_n_actions):
            samples.append((qf(p1, obs, rand[i]), qf(p2, obs, rand[i])))
        a_pi, _ = sample(actor, obs, k_pi)
        a_pi = jax.lax.stop_gradient(a_pi)
        samples.append((qf(p1, obs, a_pi), qf(p2, obs, a_pi)))
        a_pin, _ = sample(actor, batch["next_obs"], k_pin)
        a_pin = jax.lax.stop_gradient(a_pin)
        samples.append((qf(p1, obs, a_pin), qf(p2, obs, a_pin)))

        q1_cat = jnp.stack([s[0] for s in samples])  # (k, n)
        q2_cat = jnp.stack([s[1] for s in samples])
        q1_data = qf(p1, obs, batch["actions"])
        q2_data = qf(p2, obs, batch["actions"])
        pen1 = jnp.mean(jax.scipy.special.logsumexp(q1_cat, axis=0)
                        - q1_data)
        pen2 = jnp.mean(jax.scipy.special.logsumexp(q2_cat, axis=0)
                        - q2_data)
        return self.cql_alpha * (pen1 + pen2)


@dataclasses.dataclass
class CQLConfig:
    input_path: str = ""
    cql_alpha: float = 1.0
    cql_n_actions: int = 4
    lr: float = 3e-4                      # actor
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005
    train_batch_size: int = 256
    updates_per_iteration: int = 100
    hidden: tuple = (64, 64)
    seed: int = 0
    env: Union[str, Any] = "Pendulum-v1"  # only needed for evaluate()

    def build(self) -> "CQL":
        return CQL(self)


class CQL:
    """Offline training loop: dataset -> replay minibatches -> jitted
    conservative SAC updates. No environment interaction."""

    def __init__(self, config: CQLConfig):
        self.config = config
        frags: List[dict] = list(JsonReader(config.input_path))
        if not frags:
            raise ValueError(f"no data under {config.input_path}")
        obs_dim = np.asarray(frags[0]["obs"], np.float32).shape[-1]
        act = np.asarray(frags[0]["actions"])
        if act.dtype.kind in "iub":
            raise ValueError("CQL is continuous-control (got int actions)")
        action_dim = 1 if act.ndim == 1 else act.shape[-1]
        # action bound from the data (the env's scale isn't in the log)
        a_max = max(float(np.abs(np.asarray(f["actions"])).max())
                    for f in frags)
        self.replay = ReplayBuffer(
            capacity=sum(len(f["actions"]) for f in frags),
            seed=config.seed)
        for f in frags:
            t = transitions_from_fragment(f)
            if t["actions"].ndim == 1:
                t["actions"] = t["actions"][:, None]
            self.replay.add_fragment(t)
        self.learner = CQLLearner(
            obs_dim, action_dim, hidden=tuple(config.hidden),
            actor_lr=config.lr, critic_lr=config.critic_lr,
            alpha_lr=config.alpha_lr, gamma=config.gamma, tau=config.tau,
            action_scale=max(a_max, 1e-3), seed=config.seed,
            cql_alpha=config.cql_alpha,
            cql_n_actions=config.cql_n_actions)
        self.iteration = 0

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        metrics: Dict[str, float] = {}
        agg: Dict[str, List[float]] = {}
        for _ in range(self.config.updates_per_iteration):
            m = self.learner.update(
                self.replay.sample(self.config.train_batch_size))
            for k, v in m.items():
                agg.setdefault(k, []).append(v)
        metrics = {k: float(np.mean(v)) for k, v in agg.items()}
        metrics["training_iteration"] = self.iteration
        metrics["dataset_size"] = len(self.replay)
        return metrics

    def evaluate(self, num_episodes: int = 5,
                 seed: int = 100) -> Dict[str, float]:
        """Deterministic (mean-action) rollouts of the learned actor."""
        from ray_tpu.rl.envs import make_env
        from ray_tpu.rl.module import np_continuous_dist

        env = make_env(self.config.env, seed=seed)
        actor = {k: np.asarray(v) for k, v in self.learner.actor.items()}
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed + ep)
            total, done = 0.0, False
            while not done:
                mu, _ = np_continuous_dist(actor, np.asarray(obs)[None])
                a = np.tanh(mu[0]) * actor["action_scale"]
                obs, r, term, trunc, _ = env.step(a)
                total += r
                done = term or trunc
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns))}
