"""DQN: off-policy Q-learning with replay and a target network.

Reference: ``rllib/algorithms/dqn/`` (replay-buffer driven
``training_step``, double-Q target, periodic target-net sync). TPU
framing: the update is one jitted double-DQN step over a replayed
minibatch — Q-network matmuls land on the MXU, the argmax/gather are
cheap vector ops; replay sampling stays in numpy on host.

Exploration: env runners sample categorically from softmax(outputs)
(see module.np_sample_action), so running them on Q-values gives
Boltzmann (soft-Q) exploration — one of the reference's stock DQN
exploration strategies — with no runner-side special casing. Early
near-uniform Q-values explore broadly; as Q-gaps grow the policy
sharpens toward greedy.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.replay import (  # noqa: F401  (re-export)
    ReplayBuffer,
    transitions_from_fragment,
)
from ray_tpu.rl.module import init_policy_params, jax_forward


# transitions_from_fragment / ReplayBuffer live in rl/replay.py
# (shared with SAC); re-exported here for back-compat.


class DQNLearner:
    """Double-DQN update with Huber loss + periodic target sync."""

    def __init__(self, params: Dict[str, np.ndarray], *, lr: float,
                 gamma: float, target_update_freq: int):
        import jax
        import optax

        self._params = jax.device_put(params)
        self._target = jax.device_put(params)
        self._gamma = gamma
        self._freq = max(1, target_update_freq)
        self._updates = 0
        self._opt = optax.adam(lr)
        self._opt_state = self._opt.init(self._params)
        self._step = self._build_step()

    def _build_step(self):
        import jax
        import jax.numpy as jnp

        gamma = self._gamma

        def loss_fn(params, target, batch):
            q, _ = jax_forward(params, batch["obs"])
            q_next_online, _ = jax_forward(params, batch["next_obs"])
            q_next_target, _ = jax_forward(target, batch["next_obs"])
            # double-DQN: online net picks the action, target net rates it
            next_a = jnp.argmax(q_next_online, axis=-1)
            next_q = jnp.take_along_axis(
                q_next_target, next_a[:, None], axis=-1)[:, 0]
            td_target = batch["rewards"] + gamma * next_q * \
                (1.0 - batch["dones"])
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
            err = q_taken - jax.lax.stop_gradient(td_target)
            huber = jnp.where(jnp.abs(err) <= 1.0, 0.5 * err * err,
                              jnp.abs(err) - 0.5)
            return huber.mean(), {"td_error_mean": jnp.abs(err).mean(),
                                  "q_mean": q_taken.mean()}

        @jax.jit
        def step(params, target, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target, batch)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = __import__("optax").apply_updates(params, updates)
            return params, opt_state, loss, aux

        return step

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        jb["rewards"] = jb["rewards"].astype(jnp.float32)
        jb["dones"] = jb["dones"].astype(jnp.float32)
        self._params, self._opt_state, loss, aux = self._step(
            self._params, self._target, self._opt_state, jb)
        self._updates += 1
        if self._updates % self._freq == 0:
            self._target = self._params
        return {"loss": float(loss),
                **{k: float(v) for k, v in aux.items()}}

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self._params.items()}


class DQN(Algorithm):
    """Sample → replay → N minibatch updates per iteration."""

    def __init__(self, config: "DQNConfig"):
        super().__init__(config)
        params = init_policy_params(
            self._env_probe["obs_size"], self._env_probe["num_actions"],
            hidden=tuple(config.hidden), seed=config.seed)
        self.learner = DQNLearner(
            params, lr=config.lr, gamma=config.gamma,
            target_update_freq=config.target_update_freq)
        self.replay = ReplayBuffer(config.replay_capacity,
                                   seed=config.seed)

    def get_weights(self):
        return self.learner.get_weights()

    def training_step(self) -> Dict[str, Any]:
        cfg: DQNConfig = self.config  # type: ignore[assignment]
        fragments = self._sample_fragments()
        if not fragments:
            raise RuntimeError("no healthy env runners produced samples")
        returns: List[float] = []
        for f in fragments:
            self.replay.add_fragment(transitions_from_fragment(f))
            returns.extend(f["episode_returns"])
        metrics: Dict[str, float] = {}
        if len(self.replay) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                metrics = self.learner.update(
                    self.replay.sample(cfg.train_batch_size))
        self._weights_version += 1
        self._return_window = (self._return_window + returns)[-100:]
        return {
            "env_runners": {
                "episode_return_mean": self.episode_return_mean(),
                "num_episodes": len(returns),
                "num_env_steps_sampled": sum(
                    len(f["obs"]) for f in fragments),
                "num_healthy_workers":
                    self.env_runner_group.num_healthy_actors(),
            },
            "learners": {"default_policy": metrics},
            "replay_buffer_size": len(self.replay),
        }


@dataclasses.dataclass
class DQNConfig(AlgorithmConfig):
    lr: float = 1e-3
    record_next_obs: bool = True   # off-policy TD needs true successors
    replay_capacity: int = 50_000
    learning_starts: int = 500
    train_batch_size: int = 64
    updates_per_iteration: int = 16
    target_update_freq: int = 32
    algo_class = DQN
