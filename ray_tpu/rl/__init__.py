"""RL library — RLlib-equivalent stack, TPU-first.

Reference architecture (SURVEY.md §3.5, reference ``rllib/``): an
``Algorithm`` drives a sample→learn loop over an EnvRunner actor fleet
(CPU) and a Learner group (accelerator). Divergences for TPU: the Learner
is a JAX/optax pure-function SGD step (pjit-able onto a TPU mesh), env
runners are numpy-only processes (no accelerator runtime in rollout
workers), and fleet fan-out goes through :class:`FaultTolerantActorManager`
exactly as the reference does (``rllib/utils/actor_manager.py:198``).
"""

from ray_tpu.rl.actor_manager import FaultTolerantActorManager  # noqa: F401
from ray_tpu.rl.algorithm import (  # noqa: F401
    Algorithm,
    AlgorithmConfig,
    PPO,
    PPOConfig,
)
from ray_tpu.rl.dqn import (  # noqa: F401
    DQN,
    DQNConfig,
    DQNLearner,
    ReplayBuffer,
)
from ray_tpu.rl.envs import (  # noqa: F401
    CartPoleEnv,
    JaxCartPole,
    make_env,
    register_jax_env,
)
from ray_tpu.rl.podracer import (  # noqa: F401
    Anakin,
    FragmentBatch,
    PodracerConfig,
    PodracerError,
    SebulbaHandle,
)
from ray_tpu.rl.impala import (  # noqa: F401,E402
    IMPALA,
    IMPALAConfig,
    IMPALALearner,
)
from ray_tpu.rl.connectors import (  # noqa: F401
    Connector,
    ConnectorPipeline,
    FrameStack,
    Lambda,
    ObsNormalizer,
)
from ray_tpu.rl.multi_agent import (  # noqa: F401
    CoordinationGameEnv,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rl.cql import (  # noqa: F401
    CQL,
    CQLConfig,
    CQLLearner,
)
from ray_tpu.rl.marwil import (  # noqa: F401
    MARWIL,
    MARWILConfig,
)
from ray_tpu.rl.offline import (  # noqa: F401
    BC,
    BCConfig,
    JsonReader,
    JsonWriter,
    collect,
)

from ray_tpu.rl.sac import (  # noqa: F401
    SAC,
    SACConfig,
    SACLearner,
)
from ray_tpu.rl.appo import (  # noqa: F401
    APPO,
    APPOConfig,
    APPOLearner,
)
from ray_tpu.rl.dreamerv3 import (  # noqa: F401
    DreamerV3,
    DreamerV3Config,
    DreamerV3Learner,
)

from ray_tpu.util.usage import record_library_usage as _record_usage
_record_usage("rl")
del _record_usage
