"""APPO: asynchronous PPO — IMPALA's decoupled sample/learn architecture
with PPO's clipped surrogate objective on V-trace-corrected advantages.

Reference: ``rllib/algorithms/appo/appo.py`` (APPO subclasses IMPALA)
and ``appo_learner.py`` / ``default_appo_rl_module.py``: behavior-policy
importance ratios feed both the V-trace value correction and the clip
surrogate; an optional KL penalty toward the behavior policy stabilizes
aggressively-async runs (reference default ``use_kl_loss=False``).

Everything but the loss rides :mod:`ray_tpu.rl.impala`: aggregator
actors, the never-blocking sample router, LearnerGroup sharding, and the
broadcast cadence are shared code paths, exactly like the reference's
subclassing structure. TPU framing: same single jitted fixed-shape
update as IMPALA.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import numpy as np

from ray_tpu.rl.impala import IMPALA, IMPALAConfig, IMPALALearner
from ray_tpu.rl.module import jax_forward


class APPOLearner(IMPALALearner):
    """IMPALA learner with the PPO clip surrogate (+ optional KL):
    overrides ONLY the loss hook; v-trace and the jitted step/grad/apply
    scaffolding are the shared IMPALA code paths."""

    def __init__(self, params, *, clip: float = 0.2,
                 kl_coeff: float = 0.0, **kwargs):
        self._clip = clip
        self._kl_coeff = kl_coeff
        super().__init__(params, **kwargs)

    def _make_loss_fn(self, gamma, vf_c, ent_c, rho_bar, c_bar):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl.impala import vtrace_corrections

        clip, kl_coeff = self._clip, self._kl_coeff

        def loss_fn(params, batch):
            logits, values = jax_forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None].astype(jnp.int32),
                axis=1)[:, 0]
            ratio = jnp.exp(logp - batch["logp"])
            rho = jax.lax.stop_gradient(ratio)
            vs, adv = vtrace_corrections(
                values, batch, rho, gamma=gamma, rho_bar=rho_bar,
                c_bar=c_bar)
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            # PPO clip surrogate on the v-trace advantages (the APPO
            # difference vs IMPALA's plain -logp * adv)
            surrogate = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
            pi_loss = -jnp.mean(surrogate)
            vf_loss = jnp.mean((values - vs) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
            # KL(behavior || current) estimated from the sampled actions
            kl = jnp.mean(batch["logp"] - logp)
            total = (pi_loss + vf_c * vf_loss - ent_c * entropy
                     + kl_coeff * kl)
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy, "kl": kl,
                           "mean_ratio": jnp.mean(ratio)}

        return loss_fn


class APPO(IMPALA):
    """Async PPO driver — IMPALA's training_step, APPO's loss."""


@dataclasses.dataclass
class APPOConfig(IMPALAConfig):
    clip: float = 0.2
    kl_coeff: float = 0.0            # reference default: use_kl_loss=False
    lr: float = 3e-4
    entropy_coeff: float = 0.01

    @property
    def algo_class(self):
        return APPO

    def learner_cls(self):
        return APPOLearner

    def learner_kwargs(self) -> dict:
        kw = super().learner_kwargs()
        kw.update(clip=self.clip, kl_coeff=self.kl_coeff)
        return kw
