"""MARWIL: monotonic advantage re-weighted imitation learning.

Reference: ``rllib/algorithms/marwil/marwil.py`` — offline learning that
interpolates between behavior cloning (beta=0) and advantage-filtered
imitation (beta>0): each logged action's log-likelihood is weighted by
``exp(beta * A(s, a) / c)`` where A comes from a value function trained
on the logged returns and ``c`` is a running advantage norm (the
reference's moving-average normalizer, ``marwil.py`` vf/beta losses).

TPU framing: one jitted update on the shared policy+value MLP
(``rl/module.py`` — same net PPO uses, so the value head is free); the
whole minibatch computes as a single fused forward/backward.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Union

import numpy as np

from ray_tpu.rl.offline import JsonReader


def returns_to_go(rewards: np.ndarray, dones: np.ndarray,
                  gamma: float) -> np.ndarray:
    """Per-step discounted return to go, cut at episode boundaries;
    fragment tails bootstrap 0 (standard offline simplification)."""
    out = np.zeros_like(rewards, dtype=np.float32)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        if dones[t]:
            acc = 0.0
        acc = float(rewards[t]) + gamma * acc
        out[t] = acc
    return out


@dataclasses.dataclass
class MARWILConfig:
    input_path: str = ""
    beta: float = 1.0              # 0 = plain behavior cloning
    lr: float = 1e-3
    vf_coeff: float = 1.0
    gamma: float = 0.99
    num_epochs: int = 1
    minibatch_size: int = 256
    # running advantage normalizer momentum (reference moving-average)
    norm_momentum: float = 1e-2
    hidden: tuple = (64, 64)
    seed: int = 0
    env: Union[str, Any] = "CartPole-v1"  # only needed for evaluate()

    def build(self) -> "MARWIL":
        return MARWIL(self)


class MARWIL:
    def __init__(self, config: MARWILConfig):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rl.module import init_policy_params, jax_forward

        self.config = config
        obs_l: List[np.ndarray] = []
        act_l: List[np.ndarray] = []
        ret_l: List[np.ndarray] = []
        for frag in JsonReader(config.input_path):
            obs_l.append(np.asarray(frag["obs"], np.float32))
            act_l.append(np.asarray(frag["actions"], np.int32))
            ret_l.append(returns_to_go(
                np.asarray(frag["rewards"], np.float32),
                np.asarray(frag["dones"], np.bool_), config.gamma))
        self._obs = np.concatenate(obs_l)
        self._actions = np.concatenate(act_l)
        self._returns = np.concatenate(ret_l)
        self.params = init_policy_params(
            self._obs.shape[-1], int(self._actions.max()) + 1,
            hidden=tuple(config.hidden), seed=config.seed)
        self._opt = optax.adam(config.lr)
        self._opt_state = self._opt.init(self.params)
        # running E[A^2] — the advantage scale c in exp(beta * A / c).
        # Seeded from the return variance so the first minibatches don't
        # see exp(beta * A / 1) blow-ups while the average warms up.
        var0 = float(np.mean((self._returns - self._returns.mean()) ** 2))
        self._ms_adv = np.float32(var0 if var0 > 0 else 1.0)
        self.iteration = 0
        beta, vf_c, mom = config.beta, config.vf_coeff, config.norm_momentum

        def loss(params, obs, actions, rets, ms_adv):
            logits, value = jax_forward(params, obs)
            adv = rets - value
            vf_loss = jnp.mean(adv ** 2)
            ms_new = (1 - mom) * ms_adv + mom * jax.lax.stop_gradient(
                jnp.mean(adv ** 2))
            c = jnp.sqrt(ms_new) + 1e-8
            w = jnp.exp(jnp.clip(
                beta * jax.lax.stop_gradient(adv) / c, -10.0, 10.0))
            logp = jax.nn.log_softmax(logits)
            logp_a = jnp.take_along_axis(
                logp, actions[:, None].astype(jnp.int32), axis=-1)[:, 0]
            pi_loss = -jnp.mean(w * logp_a)
            return pi_loss + vf_c * vf_loss, (pi_loss, vf_loss, ms_new)

        @jax.jit
        def step(params, opt_state, obs, actions, rets, ms_adv):
            (l, (pi_l, vf_l, ms_new)), g = jax.value_and_grad(
                loss, has_aux=True)(params, obs, actions, rets, ms_adv)
            updates, opt_state = self._opt.update(g, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state,
                    l, pi_l, vf_l, ms_new)

        self._step = step
        self._rng = np.random.default_rng(config.seed)

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        n = len(self._obs)
        mb = min(self.config.minibatch_size, n)
        tot, pi, vf = [], [], []
        for _ in range(self.config.num_epochs):
            order = self._rng.permutation(n)
            for i in range(0, n - mb + 1, mb):
                idx = order[i:i + mb]
                (self.params, self._opt_state, l, pl, vl,
                 self._ms_adv) = self._step(
                    self.params, self._opt_state, self._obs[idx],
                    self._actions[idx], self._returns[idx], self._ms_adv)
                tot.append(float(l))
                pi.append(float(pl))
                vf.append(float(vl))
        return {"training_iteration": self.iteration,
                "total_loss": float(np.mean(tot)),
                "policy_loss": float(np.mean(pi)),
                "vf_loss": float(np.mean(vf)),
                "advantage_norm": float(np.sqrt(self._ms_adv))}

    def action_probs(self, obs: np.ndarray) -> np.ndarray:
        from ray_tpu.rl.module import np_forward

        logits, _ = np_forward(
            {k: np.asarray(v) for k, v in self.params.items()},
            np.asarray(obs, np.float32))
        e = np.exp(logits - logits.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)

    def evaluate(self, num_episodes: int = 5,
                 seed: int = 100) -> Dict[str, float]:
        from ray_tpu.rl.envs import make_env
        from ray_tpu.rl.module import np_forward

        env = make_env(self.config.env, seed=seed)
        params = {k: np.asarray(v) for k, v in self.params.items()}
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed + ep)
            total, done = 0.0, False
            while not done:
                logits, _ = np_forward(params, np.asarray(obs)[None])
                obs, r, term, trunc, _ = env.step(int(logits[0].argmax()))
                total += r
                done = term or trunc
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns))}
