"""Podracer RL scale-out: Sebulba (split acting/learning) + Anakin.

Reference: "Podracer architectures for scalable Reinforcement Learning"
(Hessel et al., arXiv 2104.06272). Two architectures, both driven from a
:class:`PodracerConfig` via ``algo.scale_out(...)``:

- **Sebulba** splits acting and learning onto separate actor fleets (on
  real meshes, separate device slices via
  ``parallel.stage_device_slices``).  N runner actors each wrap a
  vectorized :class:`~ray_tpu.rl.env_runner.EnvRunner` and stream rollout
  fragments as ONE sealed :class:`FragmentBatch` fused object per sample
  (the data/shuffle.py ``FusedPartitions`` pattern: stacked columns are
  the out-of-band pickle-5 buffers, so the learner maps them zero-copy
  from the shm arena).  Only the small object REF crosses the
  runner→queue→learner hop, over depth-1
  :class:`~ray_tpu.graph.channels.ShmChannel` edges with every loop
  parked as a resident actor call (train/pipeline.py's topology) — the
  steady state costs zero per-fragment driver RPCs.  Policy params flow
  the other way as a broadcast object: the learner ``put``s its weights
  once per update and fans the (version, ref) pair out on per-runner
  param channels; fragments carry the version they were acted under, so
  the learner measures policy lag per batch and can bound it
  (``max_policy_lag``) by dropping stale fragments.
- **Anakin** is the fully-jitted act+learn step for in-graph envs
  (``rl/envs.py`` ``JaxCartPole``): one compiled program runs
  ``lax.scan`` over env-step + policy-step, an in-graph GAE reverse
  scan, and the PPO update — no object plane on the hot path.

Failure contract (chaos-hardened, ``common/faults.py`` points
``rl.fragment.push`` / ``rl.params.broadcast``): a dropped handoff is
counted and skipped, never fatal; a SIGKILLed runner surfaces as a typed
event on the driver, which re-spawns a replacement onto the SAME channel
segments (the shm robust mutex recovers an owner-died lock, and the
param channel retains the last broadcast, so the replacement re-reads
current weights without a fresh round-trip); a dead learner or queue
raises :class:`PodracerError` from the driver's watched waits instead of
hanging a channel read.  The synchronous ``Algorithm.train()`` loop is
the parity oracle: ``sync_weights=True`` runs the same lock-step
schedule over this substrate and must reproduce its updates exactly.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import time
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.common import faults
from ray_tpu.core_worker import serialization as _ser
from ray_tpu.graph.channels import NO_MESSAGE, ChannelClosed, ShmChannel

__all__ = [
    "PodracerConfig",
    "PodracerError",
    "FragmentBatch",
    "SebulbaHandle",
    "Anakin",
    "scale_out",
]

_BROADCAST_TIMEOUT_S = 0.25  # per-runner param write before skipping


class PodracerError(RuntimeError):
    """A Sebulba stage died or a Podracer op exceeded its deadline."""


@dataclasses.dataclass
class PodracerConfig:
    """Scale-out plan consumed by ``algo.scale_out(...)``.

    Sebulba knobs: ``num_runners`` actors each running
    ``envs_per_runner`` envs (defaults to the algo config's
    ``num_envs_per_env_runner``); the learner updates once per
    ``fragments_per_update`` per-env fragments (default: one full round,
    ``num_runners * envs_per_runner`` — the sync loop's batch).
    ``queue_policy`` is ``"block"`` (lossless backpressure) or
    ``"drop_oldest"`` (replay-buffer semantics: acting never stalls on a
    busy learner; the freshest ``queue_capacity`` batches survive).
    ``max_policy_lag`` drops fragments more than that many weight
    versions stale; ``sync_weights=True`` is the lock-step parity-oracle
    schedule (runners block for each new broadcast, lag is always 0).
    Anakin knobs: ``batch_envs`` in-graph env copies per jitted step.
    """

    mode: str = "sebulba"  # "sebulba" | "anakin"
    num_runners: int = 2
    envs_per_runner: Optional[int] = None
    fragment_length: Optional[int] = None
    fragments_per_update: Optional[int] = None
    queue_capacity: int = 8
    queue_policy: str = "block"
    max_policy_lag: Optional[int] = None
    sync_weights: bool = False
    channel_capacity: int = 1 << 20
    io_timeout_s: float = 120.0
    # anakin
    batch_envs: int = 32


# ---------------------------------------------------------------------------
# FragmentBatch: one sealed fused object per runner sample
# ---------------------------------------------------------------------------

class FragmentBatch:
    """All per-env fragments of one runner ``sample()`` in ONE object.

    ``columns`` stacks each fragment column over the runner's F envs —
    ``(F, T, ...)`` arrays (plus ``last_value`` as ``(F,)``) — and ships
    as the object's out-of-band pickle-5 buffers: the runner's ``put``
    is one memcpy into the shm arena and the learner's ``get`` aliases
    the shared pages (read-only views; batch assembly copies out of
    them, so no alias outlives the update).  ``meta`` carries the weight
    version the fragments were acted under, the producing runner index,
    per-env episode returns, and the runner's cumulative counters.
    """

    __slots__ = ("columns", "meta")

    _STACKED = ("obs", "actions", "rewards", "dones", "terminated",
                "logp", "values", "next_obs", "is_first")

    def __init__(self, columns: Dict[str, np.ndarray], meta: Dict[str, Any]):
        self.columns = columns
        self.meta = meta

    @classmethod
    def from_fragments(cls, fragments: List[Dict[str, Any]], *,
                       runner: int, counters: Dict[str, int]
                       ) -> "FragmentBatch":
        columns = {k: np.stack([f[k] for f in fragments])
                   for k in cls._STACKED if k in fragments[0]}
        columns["last_value"] = np.asarray(
            [f["last_value"] for f in fragments], np.float32)
        if "state_in" in fragments[0]:
            for k in fragments[0]["state_in"]:
                columns["state_in_" + k] = np.stack(
                    [f["state_in"][k] for f in fragments])
        meta = {
            "version": int(fragments[0]["weights_version"]),
            "runner": int(runner),
            "episode_returns": [[float(r) for r in f["episode_returns"]]
                                for f in fragments],
            "counters": {k: int(v) for k, v in counters.items()},
        }
        return cls(columns, meta)

    @property
    def num_fragments(self) -> int:
        return len(self.columns["last_value"])

    def to_fragments(self) -> List[Dict[str, Any]]:
        """Per-env fragment dicts exactly as ``EnvRunner.sample()``
        returns them — columns are VIEWS aliasing the fused payload."""
        state_keys = [k for k in self.columns if k.startswith("state_in_")]
        out = []
        for i in range(self.num_fragments):
            frag = {k: self.columns[k][i]
                    for k in self._STACKED if k in self.columns}
            frag["last_value"] = float(self.columns["last_value"][i])
            frag["episode_returns"] = self.meta["episode_returns"][i]
            frag["weights_version"] = self.meta["version"]
            if state_keys:
                frag["state_in"] = {k[len("state_in_"):]: self.columns[k][i]
                                    for k in state_keys}
            out.append(frag)
        return out

    def __reduce__(self):
        return (FragmentBatch, (self.columns, self.meta))


def _fragment_batch_safe(v, budget) -> bool:
    # columns must be plain non-object ndarrays (the OOB buffers); meta
    # is small scalar/list/dict data the C pickler handles — anything
    # else falls back to the cloudpickle meta path (correct, just not
    # the zero-copy fast frame).
    return (isinstance(v.columns, dict)
            and all(isinstance(a, np.ndarray) and not a.dtype.hasobject
                    for a in v.columns.values())
            and _ser._plain_safe(v.meta, 4, budget))


_ser.register_plain_safe(FragmentBatch, _fragment_batch_safe)


# ---------------------------------------------------------------------------
# Sebulba stage actors (resident loops parked on channel I/O)
# ---------------------------------------------------------------------------

class _SebulbaRunner:
    """Acting stage: wraps a vectorized EnvRunner (inheriting its
    episode/recurrent-state threading across fragment boundaries) and
    streams sealed FragmentBatch refs until its param channel closes."""

    def __init__(self, blob: bytes, worker_index: int):
        import cloudpickle

        from ray_tpu.rl.env_runner import EnvRunner

        spec = cloudpickle.loads(blob)
        self._spec = spec
        self._worker_index = worker_index
        self._runner = EnvRunner(
            spec["env_spec"], seed=spec["seed"], worker_index=worker_index,
            connectors=spec["connectors"], num_envs=spec["num_envs"],
            module_to_env_connectors=spec["module_to_env_connectors"],
            record_next_obs=spec["record_next_obs"])
        self._weights_ref = None  # pins the arena pages our params alias
        self._stats = {"env_steps": 0, "fragments_produced": 0,
                       "push_drops": 0, "param_refreshes": 0,
                       "param_fetch_failures": 0}

    def pid(self) -> int:
        return os.getpid()

    def _refresh_params(self, param_ch: ShmChannel, block: bool) -> bool:
        """Pull the latest broadcast if any; False = channel closed
        (clean stop). A fetch failure (e.g. the broadcast object's
        version was retired before a late/respawned reader resolved it)
        retries on the next poll — never fatal."""
        import ray_tpu

        try:
            if block:
                msg = param_ch.read(timeout_s=self._spec["io_timeout_s"])
            else:
                msg = param_ch.read_nowait()
                if msg is NO_MESSAGE:
                    return True
        except ChannelClosed:
            return False
        try:
            weights = ray_tpu.get(msg["ref"], timeout=30.0)
        except Exception:  # noqa: BLE001 — stale ref; next broadcast heals
            self._stats["param_fetch_failures"] += 1
            return True
        self._runner.set_weights(weights, msg["version"])
        self._weights_ref = msg["ref"]
        self._stats["param_refreshes"] += 1
        return True

    def run_acting(self, param_ch: ShmChannel,
                   frag_ch: ShmChannel) -> Dict[str, int]:
        import ray_tpu

        sync = self._spec["sync_weights"]
        T = self._spec["fragment_length"]
        try:
            ok = self._refresh_params(param_ch, block=True)
            while ok:
                frags = self._runner.sample(T)
                if not isinstance(frags, list):
                    frags = [frags]
                self._stats["fragments_produced"] += len(frags)
                self._stats["env_steps"] += len(frags) * T
                batch = FragmentBatch.from_fragments(
                    frags, runner=self._worker_index, counters=self._stats)
                try:
                    ref = ray_tpu.put(batch)
                    faults.fault_point("rl.fragment.push")
                    frag_ch.write(
                        {"ref": ref, "version": batch.meta["version"],
                         "runner": self._worker_index},
                        timeout_s=self._spec["io_timeout_s"])
                except faults.FaultInjected:
                    self._stats["push_drops"] += len(frags)
                except TimeoutError:
                    # queue wedged past the io deadline: drop the batch
                    # and keep acting — a stalled consumer must not kill
                    # the producer (the driver sees the drop count)
                    self._stats["push_drops"] += len(frags)
                except ChannelClosed:
                    break
                ok = self._refresh_params(param_ch, block=sync)
        finally:
            # closure cascades to the queue whether we stop cleanly or die
            frag_ch.close()
        return dict(self._stats)


class _FragmentQueue:
    """Bounded queue/replay stage between the runner fleet and the
    learner: round-robin drains every runner channel (a dead runner
    simply stops yielding — the learner keeps stepping on the rest),
    then forwards FIFO to the learner with the live queue depth stamped
    on each message.  ``block`` policy stops draining when full
    (backpressure reaches the runners through their depth-1 channels);
    ``drop_oldest`` evicts the stalest batch instead, so acting never
    stalls on a busy learner."""

    def pid(self) -> int:
        return os.getpid()

    def run_queue(self, in_chs: List[ShmChannel], out_ch: ShmChannel,
                  capacity: int, policy: str) -> Dict[str, int]:
        buf: collections.deque = collections.deque()
        live = list(range(len(in_chs)))
        stats = {"forwarded": 0, "dropped": 0, "undelivered": 0}
        try:
            while live or buf:
                progressed = False
                for i in list(live):
                    if policy == "block" and len(buf) >= capacity:
                        break
                    try:
                        msg = in_chs[i].read_nowait()
                    except ChannelClosed:
                        live.remove(i)
                        continue
                    if msg is NO_MESSAGE:
                        continue
                    if len(buf) >= capacity:  # drop_oldest
                        buf.popleft()
                        stats["dropped"] += 1
                    buf.append(msg)
                    progressed = True
                if buf:
                    head = dict(buf[0])
                    head["queue_depth"] = len(buf)
                    try:
                        out_ch.write(head, timeout_s=0.05)
                        buf.popleft()
                        stats["forwarded"] += 1
                        progressed = True
                    except TimeoutError:
                        pass
                    except ChannelClosed:
                        break
                if not progressed:
                    time.sleep(0.002)
        finally:
            stats["undelivered"] += len(buf)
            out_ch.close()
        return stats


class _SebulbaLearner:
    """Learning stage: consumes fused fragment batches zero-copy,
    updates a PPOLearner, and broadcasts each new weight version as one
    put object fanned out on the per-runner param channels."""

    def __init__(self, blob: bytes):
        import cloudpickle

        self._cfg = cloudpickle.loads(blob)

    def pid(self) -> int:
        return os.getpid()

    def run_learning(self, queue_ch: ShmChannel,
                     param_chs: List[ShmChannel],
                     result_ch: ShmChannel) -> Dict[str, Any]:
        import ray_tpu

        from ray_tpu.parallel.sharding import _ensure_partitionable_rng
        from ray_tpu.rl.learner import PPOLearner, build_ppo_batch

        _ensure_partitionable_rng()
        c = self._cfg
        learner = PPOLearner(
            c["weights"], lr=c["lr"], clip=c["clip"],
            vf_coeff=c["vf_coeff"], entropy_coeff=c["entropy_coeff"],
            num_epochs=c["num_epochs"], minibatch_size=c["minibatch_size"],
            seed=c["seed"])
        pipeline = c["learner_pipeline"]
        version = 0
        update_idx = 0
        # the last few broadcast objects stay pinned so a respawned or
        # slow runner resolving an older (version, ref) pair still hits
        # a live object; anything older heals on the next broadcast
        weight_refs: collections.deque = collections.deque(maxlen=4)
        closed = [False] * len(param_chs)
        stats = {"consumed": 0, "lag_dropped": 0, "lost_batches": 0,
                 "broadcast_skips": 0, "broadcast_faults": 0, "drained": 0}
        per_runner: Dict[int, Dict[str, int]] = {}

        def broadcast():
            ref = ray_tpu.put(learner.get_weights())
            weight_refs.append(ref)
            msg = {"version": version, "ref": ref}
            for i, ch in enumerate(param_chs):
                if closed[i]:
                    continue
                try:
                    faults.fault_point("rl.params.broadcast")
                    ch.write(msg, timeout_s=(c["io_timeout_s"]
                                             if c["sync_weights"]
                                             else _BROADCAST_TIMEOUT_S))
                except faults.FaultInjected:
                    stats["broadcast_faults"] += 1
                except TimeoutError:
                    stats["broadcast_skips"] += 1
                except ChannelClosed:
                    closed[i] = True

        broadcast()
        pending: List[tuple] = []  # (runner, env_index, fragment)
        lag_last = queue_depth = 0
        try:
            while True:
                try:
                    msg = queue_ch.read(timeout_s=c["io_timeout_s"])
                except ChannelClosed:
                    break
                queue_depth = msg.get("queue_depth", 0)
                try:
                    fb = ray_tpu.get(msg["ref"], timeout=30.0)
                except Exception:  # noqa: BLE001 — producer died in flight
                    stats["lost_batches"] += 1
                    continue
                per_runner[fb.meta["runner"]] = fb.meta["counters"]
                frags = fb.to_fragments()
                lag_last = version - fb.meta["version"]
                if (c["max_policy_lag"] is not None
                        and lag_last > c["max_policy_lag"]):
                    stats["lag_dropped"] += len(frags)
                    continue
                stats["consumed"] += len(frags)
                pending.extend((fb.meta["runner"], e, f)
                               for e, f in enumerate(frags))
                if len(pending) < c["fragments_per_update"]:
                    continue
                if c["sync_weights"]:
                    # lock-step oracle: deterministic (runner, env) batch
                    # order, matching the sync loop's fan-in order
                    pending.sort(key=lambda t: (t[0], t[1]))
                take = [f for _, _, f in pending]
                pending = []
                batch, returns, env_steps = build_ppo_batch(
                    take, gamma=c["gamma"], lam=c["lam"],
                    seq_len=c["seq_len"] if "state_in" in take[0] else None)
                if pipeline is not None:
                    batch = pipeline(batch)
                metrics = learner.update(batch)
                version += 1
                update_idx += 1
                broadcast()
                agg = {k: sum(r.get(k, 0) for r in per_runner.values())
                       for k in ("env_steps", "fragments_produced",
                                 "push_drops")}
                record = {"update": update_idx, "version": version,
                          "metrics": metrics, "policy_lag": lag_last,
                          "queue_depth": queue_depth,
                          "env_steps_trained": env_steps,
                          "episode_returns": returns,
                          "consumed": stats["consumed"],
                          "lag_dropped": stats["lag_dropped"], **agg}
                try:
                    result_ch.write(record, timeout_s=c["io_timeout_s"])
                except ChannelClosed:
                    break
        finally:
            stats["drained"] = len(pending)
            stats["consumed"] += len(pending)
            result_ch.close()
        return {"weights": learner.get_weights(), "version": version,
                "updates": update_idx, "per_runner": per_runner, **stats}


# ---------------------------------------------------------------------------
# Driver handle
# ---------------------------------------------------------------------------

_METRICS = None


def _instruments():
    global _METRICS
    if _METRICS is None:
        from ray_tpu.util.metrics import Counter, Gauge

        _METRICS = {
            "env_steps": Counter(
                "rt_rl_env_steps_total", "env steps sampled by runners"),
            "fragments_produced": Counter(
                "rt_rl_fragments_produced_total", "fragments sealed"),
            "fragments_consumed": Counter(
                "rt_rl_fragments_consumed_total", "fragments consumed"),
            "fragments_dropped": Counter(
                "rt_rl_fragments_dropped_total",
                "fragments dropped (push faults + policy lag)"),
            "learner_updates": Counter(
                "rt_rl_learner_updates_total", "learner SGD updates"),
            "runner_restarts": Counter(
                "rt_rl_runner_restarts_total", "runner respawns"),
            "queue_depth": Gauge(
                "rt_rl_queue_depth", "fragment queue depth"),
            "policy_lag": Gauge(
                "rt_rl_policy_lag", "weight versions behind, last batch"),
            "env_steps_per_s": Gauge(
                "rt_rl_env_steps_per_s", "acting throughput"),
            "learner_steps_per_s": Gauge(
                "rt_rl_learner_steps_per_s", "learner update throughput"),
        }
    return _METRICS


def _plan_placement(num_runners: int) -> Dict[str, List[str]]:
    """Best-effort acting/learning device split (the paper's Sebulba
    topology): with an even multi-device mesh the learner takes one
    contiguous slice and acting the other; single-device (CPU) hosts
    share, which is recorded rather than hidden."""
    try:
        import jax

        from ray_tpu.parallel.mesh import stage_device_slices

        devices = jax.devices()
        if len(devices) >= 2 and len(devices) % 2 == 0:
            acting, learning = stage_device_slices(2, devices)
        else:
            acting, learning = devices, devices
        return {"acting": [str(d) for d in acting],
                "learning": [str(d) for d in learning]}
    except Exception:  # noqa: BLE001 — placement is advisory
        return {"acting": [], "learning": []}


class SebulbaHandle:
    """Driver handle for a running Sebulba session: watch updates,
    inspect ``debug_state()``, ``stop()`` to drain and fold the trained
    weights back into the algorithm.  Runner death is recovered in-place
    (respawn onto the same channels); learner/queue death raises
    :class:`PodracerError` from any watched wait."""

    def __init__(self, algo, cfg: PodracerConfig):
        import cloudpickle

        import ray_tpu

        from ray_tpu.rl.learner import PPOLearner

        if not isinstance(getattr(algo, "learner", None), PPOLearner):
            raise PodracerError(
                "Sebulba scale-out drives a PPOLearner algorithm; got "
                f"{type(getattr(algo, 'learner', None)).__name__}")
        self._algo = algo
        self._cfg = cfg
        ac = algo.config
        self._num_runners = cfg.num_runners
        envs = cfg.envs_per_runner or getattr(
            ac, "num_envs_per_env_runner", 1)
        frag_len = cfg.fragment_length or ac.rollout_fragment_length
        self._fragments_per_update = (cfg.fragments_per_update
                                      or cfg.num_runners * envs)
        self.placement = _plan_placement(cfg.num_runners)
        tag = uuid.uuid4().hex[:10]
        self._channels: List[ShmChannel] = []

        def make(name):
            ch = ShmChannel(f"/rtrl_{tag}_{name}",
                            capacity=cfg.channel_capacity, num_readers=1)
            ch._handle()  # create before any actor opens it
            self._channels.append(ch)
            return ch

        self._param_chs = [make(f"p{i}") for i in range(cfg.num_runners)]
        self._frag_chs = [make(f"f{i}") for i in range(cfg.num_runners)]
        self._queue_out = make("q")
        self._result_ch = make("r")

        self._runner_blob = cloudpickle.dumps({
            "env_spec": ac.env, "seed": ac.seed, "num_envs": envs,
            "connectors": list(ac.connectors),
            "module_to_env_connectors": list(
                getattr(ac, "module_to_env_connectors", ())),
            "record_next_obs": getattr(ac, "record_next_obs", False),
            "fragment_length": frag_len, "sync_weights": cfg.sync_weights,
            "io_timeout_s": cfg.io_timeout_s,
        })
        learner_blob = cloudpickle.dumps({
            "weights": algo.get_weights(), "lr": ac.lr, "clip": ac.clip,
            "vf_coeff": ac.vf_coeff, "entropy_coeff": ac.entropy_coeff,
            "num_epochs": ac.num_epochs,
            "minibatch_size": ac.minibatch_size, "seed": ac.seed,
            "gamma": ac.gamma, "lam": ac.lam,
            "seq_len": getattr(ac, "seq_len", None),
            "fragments_per_update": self._fragments_per_update,
            "max_policy_lag": (0 if cfg.sync_weights
                               else cfg.max_policy_lag),
            "sync_weights": cfg.sync_weights,
            "io_timeout_s": cfg.io_timeout_s,
            "learner_pipeline": (algo._learner_pipeline
                                 if algo._learner_pipeline.connectors
                                 else None),
        })

        self._remote_runner = ray_tpu.remote(_SebulbaRunner)
        self._runner_refs: Dict[int, Any] = {}
        self._runner_pids: Dict[int, int] = {}
        self._runner_stats: Dict[int, Dict[int, Dict]] = {}
        for i in range(cfg.num_runners):
            self._spawn_runner(i)
        queue_actor = ray_tpu.remote(_FragmentQueue).options(
            num_cpus=0).remote()
        self._queue_ref = queue_actor.run_queue.remote(
            self._frag_chs, self._queue_out, cfg.queue_capacity,
            cfg.queue_policy)
        learner_actor = ray_tpu.remote(_SebulbaLearner).options(
            num_cpus=0).remote(learner_blob)
        self.learner_pid = ray_tpu.get(learner_actor.pid.remote())
        self._learner_ref = learner_actor.run_learning.remote(
            self._queue_out, self._param_chs, self._result_ch)
        self._actors = [queue_actor, learner_actor]

        self.events: List[Dict[str, str]] = []
        self.restarts = 0
        self._stopping = False
        self._stopped = False
        self._summary: Optional[Dict[str, Any]] = None
        self._last_record: Optional[Dict[str, Any]] = None
        self._rate_anchor = None  # (monotonic, env_steps, updates)
        self._totals = {"env_steps": 0, "fragments_produced": 0,
                        "fragments_consumed": 0, "fragments_dropped": 0,
                        "updates": 0}

    # ------------------------------------------------------------- spawning
    def _spawn_runner(self, i: int):
        import ray_tpu

        actor = self._remote_runner.options(num_cpus=0).remote(
            self._runner_blob, i)
        self._runner_pids[i] = ray_tpu.get(actor.pid.remote())
        self._runner_refs[i] = actor.run_acting.remote(
            self._param_chs[i], self._frag_chs[i])
        self._actors = getattr(self, "_actors", []) + [actor]

    # ------------------------------------------------------------- watching
    def _check_loops(self):
        import ray_tpu

        for name, ref in (("queue", self._queue_ref),
                          ("learner", self._learner_ref)):
            done, _ = ray_tpu.wait([ref], timeout=0)
            if done and not self._stopping:
                try:
                    ray_tpu.get(ref)
                    err = "loop exited before stop()"
                except Exception as e:  # noqa: BLE001 — actor death
                    err = f"{type(e).__name__}: {e}"
                self.shutdown()
                raise PodracerError(f"sebulba {name} stage died: {err}")
        for i, ref in list(self._runner_refs.items()):
            done, _ = ray_tpu.wait([ref], timeout=0)
            if not done:
                continue
            try:
                self._runner_stats[i] = ray_tpu.get(ref)
                del self._runner_refs[i]  # clean exit (stop path)
            except Exception as e:  # noqa: BLE001 — runner died
                self.events.append({
                    "type": "runner_died", "runner": str(i),
                    "error": f"{type(e).__name__}: {e}"})
                del self._runner_refs[i]
                if not self._stopping:
                    self._spawn_runner(i)
                    self.restarts += 1
                    self.events.append({"type": "runner_respawned",
                                        "runner": str(i)})
                    _instruments()["runner_restarts"].inc()

    def _watched(self, op, timeout_s: float):
        from ray_tpu.common.retry import Deadline

        deadline = Deadline(timeout_s)
        while True:
            try:
                return op(deadline.remaining(cap=0.2) or 0.0)
            except TimeoutError:
                if deadline.expired():
                    raise
                self._check_loops()

    # -------------------------------------------------------------- updates
    def wait_updates(self, n: int = 1,
                     timeout_s: float = 120.0) -> List[Dict[str, Any]]:
        """Block for the next ``n`` learner update records (each one
        weight version), ingesting them into metrics/debug state."""
        records = []
        for _ in range(n):
            try:
                rec = self._watched(
                    lambda t: self._result_ch.read(timeout_s=t), timeout_s)
            except ChannelClosed:
                # the learner closed its result stream: surface the REAL
                # cause (a dead learner/queue loop) typed before falling
                # back to the generic closed-stream error
                self._check_loops()
                raise PodracerError(
                    "learner result stream closed mid-run") from None
            self._ingest(rec)
            records.append(rec)
        return records

    def _ingest(self, rec: Dict[str, Any]):
        m = _instruments()
        t = self._totals
        deltas = {
            "env_steps": rec["env_steps"] - t["env_steps"],
            "fragments_produced": (rec["fragments_produced"]
                                   - t["fragments_produced"]),
            "fragments_consumed": rec["consumed"] - t["fragments_consumed"],
            "fragments_dropped": (rec["push_drops"] + rec["lag_dropped"]
                                  - t["fragments_dropped"]),
            "updates": rec["update"] - t["updates"],
        }
        t.update(env_steps=rec["env_steps"],
                 fragments_produced=rec["fragments_produced"],
                 fragments_consumed=rec["consumed"],
                 fragments_dropped=rec["push_drops"] + rec["lag_dropped"],
                 updates=rec["update"])
        for key in ("env_steps", "fragments_produced", "fragments_consumed",
                    "fragments_dropped"):
            if deltas[key] > 0:
                m[{"env_steps": "env_steps",
                   "fragments_produced": "fragments_produced",
                   "fragments_consumed": "fragments_consumed",
                   "fragments_dropped": "fragments_dropped"}[key]].inc(
                       deltas[key])
        if deltas["updates"] > 0:
            m["learner_updates"].inc(deltas["updates"])
        m["queue_depth"].set(rec["queue_depth"])
        m["policy_lag"].set(rec["policy_lag"])
        now = time.monotonic()
        if self._rate_anchor is not None:
            t0, steps0, upd0 = self._rate_anchor
            dt = max(now - t0, 1e-9)
            m["env_steps_per_s"].set((rec["env_steps"] - steps0) / dt)
            m["learner_steps_per_s"].set((rec["update"] - upd0) / dt)
        self._rate_anchor = (now, rec["env_steps"], rec["update"])
        self._last_record = rec
        returns = [r for frag in rec["episode_returns"] for r in frag] \
            if rec["episode_returns"] and isinstance(
                rec["episode_returns"][0], list) else rec["episode_returns"]
        self._algo._return_window = (
            self._algo._return_window + list(returns))[-100:]

    # ---------------------------------------------------------- observability
    def debug_state(self) -> Dict[str, Any]:
        from ray_tpu.util.metrics import local_snapshots

        snaps = {s["name"]: s["values"] for s in local_snapshots()
                 if s["name"].startswith("rt_rl_")}
        return {
            "mode": "sebulba",
            "placement": self.placement,
            "num_runners": self._num_runners,
            "live_runner_loops": len(self._runner_refs),
            "fragments_per_update": self._fragments_per_update,
            "restarts": self.restarts,
            "events": list(self.events),
            "totals": dict(self._totals),
            "last_record": self._last_record,
            "metrics": snaps,
        }

    # ----------------------------------------------------------------- stop
    def stop(self, timeout_s: float = 120.0) -> Dict[str, Any]:
        """Clean stop: close the param channels (runners finish their
        fragment, close their frag channels; the queue drains into the
        learner; the learner consumes the drain, closes the result
        stream and returns) — then fold the final weights back into the
        algorithm and return the session summary."""
        import ray_tpu

        from ray_tpu.common.retry import Deadline

        if self._stopped:
            return self._summary
        self._stopping = True
        deadline = Deadline(timeout_s)
        for ch in self._param_chs:
            ch.close()
        try:
            while True:  # drain result records so the learner never blocks
                try:
                    rec = self._result_ch.read(
                        timeout_s=deadline.remaining(cap=0.2) or 0.0)
                    self._ingest(rec)
                except ChannelClosed:
                    break
                except TimeoutError:
                    if deadline.expired():
                        self.shutdown()
                        raise PodracerError(
                            "stop() deadline expired draining results"
                        ) from None
                    self._check_loops()
            loop_out: Dict[str, Any] = {}
            for name, ref in [("queue", self._queue_ref),
                              ("learner", self._learner_ref)] + [
                                  (f"runner_{i}", r)
                                  for i, r in self._runner_refs.items()]:
                try:
                    loop_out[name] = ray_tpu.get(
                        ref, timeout=deadline.remaining() or 0.1)
                except Exception as e:  # noqa: BLE001 — died during stop
                    self.events.append({"type": "stop_loss", "stage": name,
                                        "error": f"{type(e).__name__}: {e}"})
        finally:
            self.shutdown()
        learner_out = loop_out.get("learner")
        if learner_out is not None:
            self._algo.learner.set_weights(learner_out["weights"])
            self._algo._weights_version = learner_out["version"]
        runner_stats = dict(self._runner_stats)
        runner_stats.update({
            int(k.split("_")[1]): v for k, v in loop_out.items()
            if k.startswith("runner_")})
        self._summary = {
            "runners": runner_stats,
            "queue": loop_out.get("queue"),
            "learner": learner_out,
            "restarts": self.restarts,
            "events": list(self.events),
            "totals": dict(self._totals),
        }
        self._stopped = True
        return self._summary

    def shutdown(self):
        """Idempotent teardown: close + unlink channels, kill actors."""
        import ray_tpu

        self._stopping = True
        for ch in self._channels:
            ch.close()
            ch.unlink()
        self._channels = []
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 — already dead
                pass
        self._actors = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if not self._stopped:
            self.shutdown()
        return False

    @property
    def runner_pids(self) -> Dict[int, int]:
        """Live runner OS pids (chaos harnesses SIGKILL these)."""
        return dict(self._runner_pids)


# ---------------------------------------------------------------------------
# Anakin: fully-jitted act+learn for in-graph envs
# ---------------------------------------------------------------------------

class Anakin:
    """One compiled program per update: ``lax.scan`` rolls the batched
    in-graph env forward under the current policy, a reverse scan
    computes GAE, and ``num_epochs`` full-batch clipped-surrogate steps
    apply — params, optimizer state, env state, and RNG all live inside
    the jitted step's carry, so the object plane never touches the hot
    path (the paper's Anakin: everything on-device, replicated via
    ``jax.jit``/``pmap`` on real meshes)."""

    def __init__(self, algo, cfg: PodracerConfig):
        import jax

        from ray_tpu.rl.envs import get_jax_env
        from ray_tpu.rl.module import is_stateful

        ac = algo.config
        weights = algo.get_weights()
        if is_stateful(weights):
            raise PodracerError(
                "Anakin mode supports feedforward modules (the whole "
                "unroll is one scan; recurrent acting state belongs to "
                "the Sebulba runners)")
        self._algo = algo
        self._env = get_jax_env(ac.env)
        self._B = cfg.batch_envs
        self._T = cfg.fragment_length or ac.rollout_fragment_length
        self._hyper = {"gamma": ac.gamma, "lam": ac.lam, "clip": ac.clip,
                       "vf_coeff": ac.vf_coeff,
                       "entropy_coeff": ac.entropy_coeff,
                       "num_epochs": ac.num_epochs, "lr": ac.lr}
        self._raw_step, self._optimizer = _build_anakin_step(
            self._env, self._T, self._hyper)
        self._step = jax.jit(self._raw_step)
        key = jax.random.PRNGKey(ac.seed)
        key, reset_key = jax.random.split(key)
        env_state, obs = self._env.reset(reset_key, self._B)
        params = jax.tree.map(jax.numpy.asarray, dict(weights))
        self._carry = (params, self._optimizer.init(params), env_state,
                       obs, key)
        self.updates = 0
        self.env_steps = 0

    def train(self, num_updates: int = 1) -> Dict[str, Any]:
        """Run ``num_updates`` jitted act+learn steps; returns throughput
        + learning metrics and folds weights back into the algorithm."""
        import jax
        import numpy as np

        t0 = time.monotonic()
        metrics = {}
        for _ in range(num_updates):
            *self._carry, metrics = self._step(*self._carry)
            self.updates += 1
            self.env_steps += self._B * self._T
        jax.block_until_ready(self._carry[0])
        dt = max(time.monotonic() - t0, 1e-9)
        params = {k: np.asarray(v) for k, v in self._carry[0].items()}
        self._algo.learner.set_weights(params)
        self._algo._weights_version += num_updates
        m = _instruments()
        m["env_steps"].inc(num_updates * self._B * self._T)
        m["learner_updates"].inc(num_updates)
        m["env_steps_per_s"].set(num_updates * self._B * self._T / dt)
        m["learner_steps_per_s"].set(num_updates / dt)
        return {"updates": self.updates, "env_steps": self.env_steps,
                "env_steps_per_s": num_updates * self._B * self._T / dt,
                "learner_steps_per_s": num_updates / dt,
                "metrics": {k: float(v) for k, v in metrics.items()}}

    def debug_state(self) -> Dict[str, Any]:
        from ray_tpu.util.metrics import local_snapshots

        return {"mode": "anakin", "batch_envs": self._B,
                "unroll_length": self._T, "updates": self.updates,
                "env_steps": self.env_steps,
                "metrics": {s["name"]: s["values"]
                            for s in local_snapshots()
                            if s["name"].startswith("rt_rl_")}}


def _build_anakin_step(env, unroll: int, hyper: Dict[str, float]):
    """Build the (unjitted) Anakin step + its optimizer; the caller jits.
    Returned signature: ``step(params, opt_state, env_state, obs, key)
    -> (params, opt_state, env_state, obs, key, metrics)``."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.rl.module import jax_forward

    gamma, lam = hyper["gamma"], hyper["lam"]
    clip, vf_c, ent_c = hyper["clip"], hyper["vf_coeff"], \
        hyper["entropy_coeff"]
    optimizer = optax.chain(optax.clip_by_global_norm(0.5),
                            optax.adam(hyper["lr"]))

    def act(carry, _):
        params, env_state, obs, ep_ret, key = carry
        key, k_act, k_env = jax.random.split(key, 3)
        logits, values = jax_forward(params, obs)
        action = jax.random.categorical(k_act, logits)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits), action[:, None], axis=1)[:, 0]
        env_state, next_obs, reward, done = env.step(env_state, action,
                                                     k_env)
        ep_ret = ep_ret + reward
        out = (obs, action, logp, values, reward,
               done.astype(jnp.float32),
               jnp.where(done, ep_ret, 0.0), done.astype(jnp.int32))
        ep_ret = jnp.where(done, 0.0, ep_ret)
        return (params, env_state, next_obs, ep_ret, key), out

    def gae(rewards, values, dones, last_value):
        # reverse scan over the unroll, masked at episode boundaries —
        # the in-graph twin of learner.compute_gae
        def body(carry, xs):
            g, next_v = carry
            r, v, d = xs
            nonterm = 1.0 - d
            delta = r + gamma * next_v * nonterm - v
            g = delta + gamma * lam * nonterm * g
            return (g, v), g

        B = rewards.shape[1]
        (_, _), adv_rev = jax.lax.scan(
            body, (jnp.zeros(B), last_value),
            (rewards[::-1], values[::-1], dones[::-1]))
        return adv_rev[::-1]

    def loss_fn(params, batch):
        logits, values = jax_forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1)[:, 0]
        ratio = jnp.exp(logp - batch["logp_old"])
        adv = batch["advantages"]
        surr = jnp.minimum(ratio * adv,
                           jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
        pi_loss = -surr.mean()
        vf_loss = jnp.mean((values - batch["value_targets"]) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
        return pi_loss + vf_c * vf_loss - ent_c * entropy, \
            {"pi_loss": pi_loss, "vf_loss": vf_loss, "entropy": entropy}

    def step(params, opt_state, env_state, obs, key):
        (params, env_state, obs, _, key), traj = jax.lax.scan(
            act, (params, env_state, obs,
                  jnp.zeros(obs.shape[0]), key), None, length=unroll)
        (obs_t, act_t, logp_t, val_t, rew_t, done_t,
         ret_sum_t, ret_cnt_t) = traj
        _, last_v = jax_forward(params, obs)
        adv = gae(rew_t, val_t, done_t, last_v)
        targets = adv + val_t
        flat = {
            "obs": obs_t.reshape((-1,) + obs_t.shape[2:]),
            "actions": act_t.reshape(-1),
            "logp_old": logp_t.reshape(-1),
            "advantages": (lambda a: (a - a.mean()) / (a.std() + 1e-8))(
                adv.reshape(-1)),
            "value_targets": targets.reshape(-1),
        }
        aux = {}
        for _ in range(int(hyper["num_epochs"])):
            (_, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, flat)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        n_done = jnp.maximum(ret_cnt_t.sum(), 1)
        aux = dict(aux)
        aux["episode_return_mean"] = ret_sum_t.sum() / n_done
        aux["episodes_completed"] = ret_cnt_t.sum().astype(jnp.float32)
        return params, opt_state, env_state, obs, key, aux

    return step, optimizer


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def scale_out(algo, cfg: PodracerConfig):
    """Dispatch ``algo.scale_out(cfg)``: Sebulba returns a live
    :class:`SebulbaHandle` (acting already streaming); Anakin returns an
    :class:`Anakin` whose ``train(n)`` runs compiled updates."""
    if cfg.mode == "sebulba":
        return SebulbaHandle(algo, cfg)
    if cfg.mode == "anakin":
        return Anakin(algo, cfg)
    raise PodracerError(f"unknown podracer mode {cfg.mode!r} "
                        "(want 'sebulba' | 'anakin')")
