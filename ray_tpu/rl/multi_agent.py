"""Multi-agent RL: multi-agent envs, policy mapping, per-policy learners.

Reference: ``rllib/env/multi_agent_env.py`` (dict-keyed obs/action/reward
protocol), ``rllib/algorithms/algorithm_config.py multi_agent()`` (policies
+ policy_mapping_fn + policies_to_train), and the per-module learner
updates of the new API stack.

Protocol (gymnasium multi-agent shape):
    reset() -> ({agent_id: obs}, info)
    step({agent_id: action})
        -> ({agent_id: obs}, {agent_id: reward}, {agent_id: terminated},
            {agent_id: truncated}, info)
Agents may appear/disappear between steps; "__all__" in terminated ends
the episode for everyone.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from ray_tpu.rl.actor_manager import FaultTolerantActorManager
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.learner import PPOLearner, compute_gae
from ray_tpu.rl.module import (get_initial_state, init_policy_params,
                               is_stateful, np_forward, np_sample_action,
                               np_stateful_sample_batch,
                               np_stateful_values)


class CoordinationGameEnv:
    """2-agent repeated matrix game: both get +1 when actions match, else
    0. Obs is each agent's OWN previous action (one-hot) — enough signal
    for two independent policies to converge on a convention. A standard
    multi-agent smoke test with a known optimum (reward_mean -> 1.0)."""

    agent_ids = ("agent_0", "agent_1")
    observation_size = 3
    num_actions = 3
    max_episode_steps = 32

    def __init__(self, seed: Optional[int] = None):
        self._steps = 0
        self._last = {a: 0 for a in self.agent_ids}

    def _obs(self):
        out = {}
        for a in self.agent_ids:
            v = np.zeros(self.observation_size, np.float32)
            v[self._last[a]] = 1.0
            out[a] = v
        return out

    def reset(self, seed: Optional[int] = None):
        self._steps = 0
        self._last = {a: 0 for a in self.agent_ids}
        return self._obs(), {}

    def step(self, actions: Dict[str, int]):
        self._steps += 1
        match = actions["agent_0"] == actions["agent_1"]
        rew = {a: 1.0 if match else 0.0 for a in self.agent_ids}
        self._last = dict(actions)
        trunc = self._steps >= self.max_episode_steps
        return (self._obs(), rew,
                {a: False for a in self.agent_ids},
                {a: trunc for a in self.agent_ids}, {})


class MultiAgentEnvRunner:
    """Rollout actor for multi-agent envs: per-agent trajectories are
    routed to per-POLICY buffers through the policy mapping (reference
    ``rllib/env/multi_agent_env_runner.py``).

    Stateful modules (rl/module.py contract) are supported on the acting
    path: each AGENT carries its own recurrent state (agents sharing a
    policy still have distinct histories), reset via ``is_first`` at
    episode boundaries. The bundled MultiAgentPPO trainer builds
    feedforward modules, so the recurrent plumbing here serves externally
    trained stateful policies (evaluation / league play)."""

    def __init__(self, env_spec, policy_mapping: Dict[str, str],
                 seed: int = 0, worker_index: int = 0):
        from ray_tpu.rl.envs import make_env

        self.env = make_env(env_spec, seed=seed + worker_index)
        self._mapping = dict(policy_mapping)  # agent_id -> policy_id
        self._rng = np.random.default_rng(seed * 99991 + worker_index)
        self._params: Dict[str, Any] = {}     # policy_id -> params
        self._obs, _ = self.env.reset(seed=seed + worker_index)
        self._ep_return = 0.0
        self._weights_version = -1
        self._agent_state: Dict[str, Dict[str, np.ndarray]] = {}
        self._agent_first: Dict[str, bool] = {}
        # per-policy zero-state template, rebuilt only on set_weights —
        # _act runs per agent per step and must not re-derive it there
        self._state_templates: Dict[str, Dict[str, np.ndarray]] = {}

    def ping(self) -> bool:
        return True

    def set_weights(self, params_by_policy: Dict[str, Any],
                    version: int = 0) -> bool:
        self._params.update(params_by_policy)
        self._weights_version = version
        self._state_templates = {
            pid: get_initial_state(p, 1)
            for pid, p in self._params.items() if is_stateful(p)}
        return True

    def _act(self, agent_id: str, obs) -> Tuple[int, float, float]:
        """One action for one agent, carrying per-agent recurrent state
        for stateful policy modules."""
        pid = self._mapping[agent_id]
        params = self._params[pid]
        if not is_stateful(params):
            a, logp, value = np_sample_action(params, obs, self._rng)
            return int(a), logp, value
        tmpl = self._state_templates[pid]
        state = self._agent_state.get(agent_id)
        if state is None or set(state) != set(tmpl) or any(
                state[k].shape != tmpl[k].shape for k in tmpl):
            state = {k: v.copy() for k, v in tmpl.items()}
            self._agent_first[agent_id] = True
        first = np.array([self._agent_first.get(agent_id, True)], bool)
        a_b, lp_b, v_b, state = np_stateful_sample_batch(
            params, np.asarray(obs, np.float32)[None], state, first,
            self._rng)
        self._agent_state[agent_id] = state
        self._agent_first[agent_id] = False
        return int(a_b[0]), float(lp_b[0]), float(v_b[0])

    def sample(self, num_steps: int) -> Dict[str, Any]:
        # Buffers are PER AGENT, not per policy: agents sharing one policy
        # still have distinct trajectories, and GAE must bootstrap along
        # each agent's own value sequence — interleaving them would make
        # every TD delta use another agent's next-state value.
        buf: Dict[str, Dict[str, list]] = {}
        episode_returns: List[float] = []
        for _ in range(num_steps):
            actions, per_agent = {}, {}
            for agent_id, obs in self._obs.items():
                a, logp, value = self._act(agent_id, obs)
                actions[agent_id] = int(a)
                per_agent[agent_id] = (obs, a, logp, value)
            next_obs, rewards, terms, truncs, _ = self.env.step(actions)
            done = terms.get("__all__", False) or all(
                terms.get(a, False) or truncs.get(a, False)
                for a in actions)
            for agent_id, (obs, a, logp, value) in per_agent.items():
                b = buf.setdefault(agent_id, {
                    "obs": [], "actions": [], "rewards": [], "dones": [],
                    "logp": [], "values": []})
                b["obs"].append(obs)
                b["actions"].append(a)
                b["rewards"].append(rewards.get(agent_id, 0.0))
                b["dones"].append(done)
                b["logp"].append(logp)
                b["values"].append(value)
            self._ep_return += float(sum(rewards.values()))
            if done:
                episode_returns.append(self._ep_return)
                self._ep_return = 0.0
                self._obs, _ = self.env.reset()
                # drop (not just re-flag) per-agent recurrent state:
                # next _act restarts from the zero template anyway, and
                # envs that mint fresh agent ids per episode must not
                # accumulate dead agents' state forever
                self._agent_state.clear()
                self._agent_first.clear()
            else:
                self._obs = next_obs
        out = {}
        for agent_id, b in buf.items():
            pid = self._mapping[agent_id]
            last_val = 0.0
            if agent_id in self._obs:
                params = self._params[pid]
                obs1 = np.asarray(self._obs[agent_id],
                                  np.float32)[None]
                if is_stateful(params):
                    last_val = float(np_stateful_values(
                        params, obs1,
                        self._agent_state.get(agent_id)
                        or get_initial_state(params, 1),
                        np.array([self._agent_first.get(agent_id, True)],
                                 bool))[0])
                else:
                    _, v = np_forward(params, obs1)
                    last_val = float(v[0])
            out[agent_id] = {
                "policy_id": pid,
                "obs": np.asarray(b["obs"], np.float32),
                "actions": np.asarray(b["actions"], np.int32),
                "rewards": np.asarray(b["rewards"], np.float32),
                "dones": np.asarray(b["dones"], np.bool_),
                "logp": np.asarray(b["logp"], np.float32),
                "values": np.asarray(b["values"], np.float32),
                "last_value": last_val,
            }
        return {"agents": out, "episode_returns": episode_returns,
                "weights_version": self._weights_version}


class MultiAgentPPO(Algorithm):
    """PPO with one learner per policy (reference: the multi-module
    LearnerGroup update path)."""

    def __init__(self, config: "MultiAgentPPOConfig"):
        import ray_tpu

        # NOTE: deliberately not calling Algorithm.__init__ — the runner
        # fleet is multi-agent-shaped.
        self.config = config
        self.iteration = 0
        self._weights_version = 0
        self._return_window: List[float] = []

        from ray_tpu.rl.envs import make_env

        env = make_env(config.env)
        obs, _ = env.reset(seed=0)
        self._mapping = {
            agent_id: config.policy_mapping_fn(agent_id)
            for agent_id in obs
        }
        self.learners: Dict[str, PPOLearner] = {}
        for pid in sorted(set(self._mapping.values())):
            any_agent = next(a for a, p in self._mapping.items() if p == pid)
            import zlib

            # crc32, not hash(): hash() is salted per process and would
            # defeat config.seed reproducibility
            params = init_policy_params(
                int(np.asarray(obs[any_agent]).size),
                int(env.num_actions), hidden=tuple(config.hidden),
                seed=config.seed + zlib.crc32(pid.encode()) % 1000)
            self.learners[pid] = PPOLearner(
                params, lr=config.lr, clip=config.clip,
                vf_coeff=config.vf_coeff,
                entropy_coeff=config.entropy_coeff,
                num_epochs=config.num_epochs,
                minibatch_size=config.minibatch_size, seed=config.seed)
        self._to_train = set(config.policies_to_train
                             or self.learners.keys())

        remote_runner = ray_tpu.remote(MultiAgentEnvRunner)
        actors = [
            remote_runner.remote(config.env, self._mapping,
                                 seed=config.seed, worker_index=i)
            for i in range(config.num_env_runners)
        ]
        self.env_runner_group = FaultTolerantActorManager(actors)

    def get_weights(self) -> Dict[str, Any]:
        return {pid: lr.get_weights() for pid, lr in self.learners.items()}

    def training_step(self) -> Dict[str, Any]:
        self._maybe_restore_runners()
        weights = self.get_weights()
        version = self._weights_version
        self.env_runner_group.foreach_actor(
            lambda a: a.set_weights.remote(weights, version))
        results = self.env_runner_group.foreach_actor(
            lambda a: a.sample.remote(self.config.rollout_fragment_length))
        fragments = [r.value for r in results if r.ok]
        if not fragments:
            raise RuntimeError("no healthy env runners produced samples")

        returns: List[float] = []
        learner_metrics: Dict[str, Dict] = {}
        for pid in self.learners:
            if pid not in self._to_train:
                continue
            # one fragment per (runner, agent) trajectory of this policy
            frags = [af for f in fragments
                     for af in f["agents"].values()
                     if af["policy_id"] == pid]
            if not frags:
                continue
            advs, targets = [], []
            for f in frags:
                a, vt = compute_gae(
                    f["rewards"], f["values"], f["dones"], f["last_value"],
                    gamma=self.config.gamma, lam=self.config.lam)
                advs.append(a)
                targets.append(vt)
            batch = {
                "obs": np.concatenate([f["obs"] for f in frags]),
                "actions": np.concatenate([f["actions"] for f in frags]),
                "logp_old": np.concatenate([f["logp"] for f in frags]),
                "advantages": np.concatenate(advs),
                "value_targets": np.concatenate(targets),
            }
            adv = batch["advantages"]
            batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
            learner_metrics[pid] = self.learners[pid].update(batch)
        for f in fragments:
            returns.extend(f["episode_returns"])
        self._weights_version += 1
        self._return_window = (self._return_window + returns)[-100:]
        return {
            "env_runners": {
                "episode_return_mean": self.episode_return_mean(),
                "num_episodes": len(returns),
                "num_healthy_workers":
                    self.env_runner_group.num_healthy_actors(),
            },
            "learners": learner_metrics,
        }


@dataclasses.dataclass
class MultiAgentPPOConfig(AlgorithmConfig):
    lam: float = 0.95
    clip: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    minibatch_size: int = 128
    policy_mapping_fn: Callable[[str], str] = lambda agent_id: agent_id
    policies_to_train: Optional[List[str]] = None
    algo_class = MultiAgentPPO

    def multi_agent(self, *, policy_mapping_fn=None,
                    policies_to_train=None) -> "MultiAgentPPOConfig":
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        if policies_to_train is not None:
            self.policies_to_train = policies_to_train
        return self
