"""Fault-tolerant fan-out to an actor fleet.

Reference: ``rllib/utils/actor_manager.py:198 FaultTolerantActorManager`` —
async fan-out with per-actor health tracking; results come back tagged with
the actor id; unhealthy actors are skipped and can be restored/replaced.
Used for EnvRunner fleets and learner groups.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CallResult:
    actor_index: int
    ok: bool
    value: Any = None
    error: Optional[BaseException] = None

    def get(self):
        if not self.ok:
            raise self.error
        return self.value


class FaultTolerantActorManager:
    def __init__(self, actors: List[Any],
                 max_remote_requests_in_flight_per_actor: int = 2):
        self._actors: Dict[int, Any] = dict(enumerate(actors))
        self._healthy: Dict[int, bool] = {i: True for i in self._actors}
        self._max_in_flight = max_remote_requests_in_flight_per_actor

    # ------------------------------------------------------------ topology
    @property
    def actors(self) -> Dict[int, Any]:
        return dict(self._actors)

    def healthy_actor_ids(self) -> List[int]:
        return [i for i, h in self._healthy.items() if h]

    def num_healthy_actors(self) -> int:
        return len(self.healthy_actor_ids())

    def set_actor_state(self, actor_index: int, healthy: bool):
        self._healthy[actor_index] = healthy

    def add_actor(self, actor: Any) -> int:
        idx = max(self._actors) + 1 if self._actors else 0
        self._actors[idx] = actor
        self._healthy[idx] = True
        return idx

    def remove_actor(self, actor_index: int):
        import ray_tpu

        actor = self._actors.pop(actor_index, None)
        self._healthy.pop(actor_index, None)
        if actor is not None:
            try:
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------- fan-out
    def foreach_actor(self, fn: Callable[[Any], Any], *,
                      healthy_only: bool = True,
                      remote_actor_ids: Optional[List[int]] = None,
                      timeout_seconds: Optional[float] = 60.0,
                      mark_unhealthy: bool = True) -> List[CallResult]:
        """``fn(actor) -> ObjectRef`` is applied to each actor (it should
        call ``.remote()``); results are fetched with per-actor fault
        isolation: one dead actor yields a failed CallResult, not an
        exception for the whole fleet."""
        import ray_tpu

        ids = remote_actor_ids if remote_actor_ids is not None else (
            self.healthy_actor_ids() if healthy_only
            else list(self._actors))
        refs: Dict[int, Any] = {}
        results: List[CallResult] = []
        for i in ids:
            try:
                refs[i] = fn(self._actors[i])
            except Exception as e:  # noqa: BLE001 — submit-side failure
                results.append(CallResult(i, False, error=e))
                if mark_unhealthy:
                    self._healthy[i] = False
        # One shared deadline bounds the WHOLE fan-out: a single stuck
        # actor costs timeout_seconds once, not once per actor.
        import time

        deadline = (None if timeout_seconds is None
                    else time.monotonic() + timeout_seconds)
        for i, ref in refs.items():
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                value = ray_tpu.get([ref], timeout=remaining)[0]
                results.append(CallResult(i, True, value=value))
            except Exception as e:  # noqa: BLE001 — actor died / timeout
                results.append(CallResult(i, False, error=e))
                if mark_unhealthy:
                    self._healthy[i] = False
        results.sort(key=lambda r: r.actor_index)
        return results

    def probe_health(self, method: str = "ping") -> List[int]:
        """Re-probe unhealthy actors; mark recovered ones healthy again."""
        import ray_tpu

        recovered = []
        for i, h in list(self._healthy.items()):
            if h:
                continue
            try:
                ray_tpu.get([getattr(self._actors[i], method).remote()],
                            timeout=5.0)
                self._healthy[i] = True
                recovered.append(i)
            except Exception:  # noqa: BLE001 — still dead
                pass
        return recovered
