"""RLModule equivalent: policy + value MLPs with twin implementations.

Reference: ``rllib/core/rl_module/`` — one module definition used in two
roles: inference-only copies on env runners, trainable copy on learners.
TPU twist: the trainable copy is pure-JAX (pjit-able); the inference copy
is pure numpy so rollout workers never load an accelerator runtime. Both
share one param pytree (dict of numpy arrays at the boundary).

Policy and value are separate towers (no shared trunk): the value
regression's large early losses otherwise dominate the shared features and
stall policy learning at this scale.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

Params = Dict[str, np.ndarray]


def init_policy_params(obs_size: int, num_actions: int,
                       hidden: Tuple[int, ...] = (64, 64),
                       seed: int = 0) -> Params:
    rng = np.random.default_rng(seed)
    params: Params = {}
    sizes = (obs_size,) + hidden

    def dense(name, fan_in, fan_out, scale):
        params[f"{name}_w"] = (rng.standard_normal((fan_in, fan_out))
                               * scale).astype(np.float32)
        params[f"{name}_b"] = np.zeros(fan_out, np.float32)

    for tower in ("p", "v"):
        for i in range(len(hidden)):
            dense(f"{tower}{i}", sizes[i], sizes[i + 1],
                  np.sqrt(2.0 / sizes[i]))
    # small-init policy head → near-uniform initial policy
    dense("pi", sizes[-1], num_actions, 0.01)
    dense("vh", sizes[-1], 1, np.sqrt(1.0 / sizes[-1]))
    return params


def _n_hidden(params) -> int:
    n = 0
    while f"p{n}_w" in params:
        n += 1
    return n


def np_forward(params: Params, obs: np.ndarray):
    """(B, obs) → (logits (B, A), value (B,)). Pure numpy (env runners)."""
    x = v = obs
    for i in range(_n_hidden(params)):
        x = np.tanh(x @ params[f"p{i}_w"] + params[f"p{i}_b"])
        v = np.tanh(v @ params[f"v{i}_w"] + params[f"v{i}_b"])
    logits = x @ params["pi_w"] + params["pi_b"]
    value = (v @ params["vh_w"] + params["vh_b"])[:, 0]
    return logits, value


def jax_forward(params, obs):
    """Same network in jnp (learners); params may be jax arrays."""
    import jax.numpy as jnp

    x = v = obs
    for i in range(_n_hidden(params)):
        x = jnp.tanh(x @ params[f"p{i}_w"] + params[f"p{i}_b"])
        v = jnp.tanh(v @ params[f"v{i}_w"] + params[f"v{i}_b"])
    logits = x @ params["pi_w"] + params["pi_b"]
    value = (v @ params["vh_w"] + params["vh_b"])[:, 0]
    return logits, value


def np_sample_action(params: Params, obs: np.ndarray,
                     rng: np.random.Generator):
    """Single-obs categorical sample → (action, logp, value)."""
    logits, value = np_forward(params, obs[None])
    logits = logits[0] - logits[0].max()
    p = np.exp(logits)
    p /= p.sum()
    action = int(rng.choice(len(p), p=p))
    return action, float(np.log(p[action] + 1e-20)), float(value[0])


def np_sample_actions_batch(params: Params, obs: np.ndarray,
                            rng: np.random.Generator):
    """Vectorized categorical sample over a batch of observations:
    (N, obs) → (actions (N,), logps (N,), values (N,)). One forward matmul
    for the whole env vector — the point of vectorized env runners
    (reference rllib/env/vector/)."""
    logits, values = np_forward(params, obs)
    logits = logits - logits.max(axis=1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(axis=1, keepdims=True)
    # Gumbel-max: one vectorized draw instead of N rng.choice calls
    g = rng.gumbel(size=p.shape)
    actions = (np.log(p + 1e-20) + g).argmax(axis=1)
    logps = np.log(p[np.arange(len(p)), actions] + 1e-20)
    return actions.astype(np.int32), logps.astype(np.float32), \
        values.astype(np.float32)


# ----------------------------------------------------------- continuous
# Tanh-squashed Gaussian policy (SAC-style, reference
# rllib/algorithms/sac/sac_learner.py + torch squashed-gaussian dist):
# trunk "c{i}" -> heads "mu" and "ls" (state-dependent log-std), plus
# "action_scale" bounds. Detected by `"mu_w" in params` — env runners
# dispatch on it with no per-algorithm branching.

LOGSTD_MIN, LOGSTD_MAX = -5.0, 2.0


def init_continuous_policy_params(obs_size: int, action_dim: int,
                                  hidden: Tuple[int, ...] = (64, 64),
                                  seed: int = 0,
                                  action_scale: float = 1.0) -> Params:
    rng = np.random.default_rng(seed)
    params: Params = {}
    sizes = (obs_size,) + hidden

    def dense(name, fan_in, fan_out, scale):
        params[f"{name}_w"] = (rng.standard_normal((fan_in, fan_out))
                               * scale).astype(np.float32)
        params[f"{name}_b"] = np.zeros(fan_out, np.float32)

    for i in range(len(hidden)):
        dense(f"c{i}", sizes[i], sizes[i + 1], np.sqrt(2.0 / sizes[i]))
    dense("mu", sizes[-1], action_dim, 0.01)
    dense("ls", sizes[-1], action_dim, 0.01)
    params["action_scale"] = np.asarray(action_scale, np.float32)
    return params


def _n_cont_hidden(params) -> int:
    n = 0
    while f"c{n}_w" in params:
        n += 1
    return n


def np_continuous_dist(params: Params, obs: np.ndarray):
    """(B, obs) → (mu (B, A), std (B, A)) of the pre-squash Gaussian."""
    x = obs
    for i in range(_n_cont_hidden(params)):
        x = np.tanh(x @ params[f"c{i}_w"] + params[f"c{i}_b"])
    mu = x @ params["mu_w"] + params["mu_b"]
    logstd = np.clip(x @ params["ls_w"] + params["ls_b"],
                     LOGSTD_MIN, LOGSTD_MAX)
    return mu, np.exp(logstd)


def np_sample_continuous_batch(params: Params, obs: np.ndarray,
                               rng: np.random.Generator):
    """(N, obs) → (actions (N, A) f32, logps (N,), values zeros (N,)).
    Values are zeros: off-policy consumers (SAC) bootstrap from their own
    critics, not runner-side value estimates."""
    mu, std = np_continuous_dist(params, obs)
    eps = rng.standard_normal(mu.shape)
    pre = mu + std * eps
    scale = float(params["action_scale"])
    act = np.tanh(pre) * scale
    logp = (-0.5 * (eps ** 2 + np.log(2 * np.pi)) - np.log(std)
            - np.log(scale * (1 - np.tanh(pre) ** 2) + 1e-6)).sum(axis=1)
    return (act.astype(np.float32), logp.astype(np.float32),
            np.zeros(len(obs), np.float32))


def is_continuous(params: Params) -> bool:
    return "mu_w" in params


def action_spec(params: Params):
    """(trailing action shape, dtype) a runner should buffer for."""
    if is_continuous(params):
        return (params["mu_b"].shape[0],), np.float32
    return (), np.int32
