"""RLModule equivalent: policy + value MLPs with twin implementations.

Reference: ``rllib/core/rl_module/`` — one module definition used in two
roles: inference-only copies on env runners, trainable copy on learners.
TPU twist: the trainable copy is pure-JAX (pjit-able); the inference copy
is pure numpy so rollout workers never load an accelerator runtime. Both
share one param pytree (dict of numpy arrays at the boundary).

Policy and value are separate towers (no shared trunk): the value
regression's large early losses otherwise dominate the shared features and
stall policy learning at this scale.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

Params = Dict[str, np.ndarray]


def init_policy_params(obs_size: int, num_actions: int,
                       hidden: Tuple[int, ...] = (64, 64),
                       seed: int = 0) -> Params:
    rng = np.random.default_rng(seed)
    params: Params = {}
    sizes = (obs_size,) + hidden

    def dense(name, fan_in, fan_out, scale):
        params[f"{name}_w"] = (rng.standard_normal((fan_in, fan_out))
                               * scale).astype(np.float32)
        params[f"{name}_b"] = np.zeros(fan_out, np.float32)

    for tower in ("p", "v"):
        for i in range(len(hidden)):
            dense(f"{tower}{i}", sizes[i], sizes[i + 1],
                  np.sqrt(2.0 / sizes[i]))
    # small-init policy head → near-uniform initial policy
    dense("pi", sizes[-1], num_actions, 0.01)
    dense("vh", sizes[-1], 1, np.sqrt(1.0 / sizes[-1]))
    return params


def _n_hidden(params) -> int:
    n = 0
    while f"p{n}_w" in params:
        n += 1
    return n


def np_forward(params: Params, obs: np.ndarray):
    """(B, obs) → (logits (B, A), value (B,)). Pure numpy (env runners)."""
    x = v = obs
    for i in range(_n_hidden(params)):
        x = np.tanh(x @ params[f"p{i}_w"] + params[f"p{i}_b"])
        v = np.tanh(v @ params[f"v{i}_w"] + params[f"v{i}_b"])
    logits = x @ params["pi_w"] + params["pi_b"]
    value = (v @ params["vh_w"] + params["vh_b"])[:, 0]
    return logits, value


def jax_forward(params, obs):
    """Same network in jnp (learners); params may be jax arrays."""
    import jax.numpy as jnp

    x = v = obs
    for i in range(_n_hidden(params)):
        x = jnp.tanh(x @ params[f"p{i}_w"] + params[f"p{i}_b"])
        v = jnp.tanh(v @ params[f"v{i}_w"] + params[f"v{i}_b"])
    logits = x @ params["pi_w"] + params["pi_b"]
    value = (v @ params["vh_w"] + params["vh_b"])[:, 0]
    return logits, value


def np_sample_action(params: Params, obs: np.ndarray,
                     rng: np.random.Generator):
    """Single-obs categorical sample → (action, logp, value)."""
    logits, value = np_forward(params, obs[None])
    logits = logits[0] - logits[0].max()
    p = np.exp(logits)
    p /= p.sum()
    action = int(rng.choice(len(p), p=p))
    return action, float(np.log(p[action] + 1e-20)), float(value[0])


def np_sample_actions_batch(params: Params, obs: np.ndarray,
                            rng: np.random.Generator):
    """Vectorized categorical sample over a batch of observations:
    (N, obs) → (actions (N,), logps (N,), values (N,)). One forward matmul
    for the whole env vector — the point of vectorized env runners
    (reference rllib/env/vector/)."""
    logits, values = np_forward(params, obs)
    logits = logits - logits.max(axis=1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(axis=1, keepdims=True)
    # Gumbel-max: one vectorized draw instead of N rng.choice calls
    g = rng.gumbel(size=p.shape)
    actions = (np.log(p + 1e-20) + g).argmax(axis=1)
    logps = np.log(p[np.arange(len(p)), actions] + 1e-20)
    return actions.astype(np.int32), logps.astype(np.float32), \
        values.astype(np.float32)
