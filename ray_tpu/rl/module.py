"""RLModule equivalent: policy + value networks with twin implementations.

Reference: ``rllib/core/rl_module/`` — one module definition used in two
roles: inference-only copies on env runners, trainable copy on learners.
TPU twist: the trainable copy is pure-JAX (pjit-able); the inference copy
is pure numpy so rollout workers never load an accelerator runtime. Both
share one param pytree (dict of numpy arrays at the boundary).

Policy and value are separate towers (no shared trunk): the value
regression's large early losses otherwise dominate the shared features and
stall policy learning at this scale.

Stateful-module contract (reference ``RLModule.get_initial_state``,
``rllib/core/rl_module/rl_module.py:653``): modules that carry recurrent
state expose

- ``get_initial_state(params, batch_size)`` → dict of per-env state
  arrays (``{}`` for feedforward modules);
- ``np_stateful_sample_batch(params, obs, state, is_first, rng)`` →
  ``(actions, logps, values, next_state)`` — the numpy acting step. The
  module owns its OWN reset semantics for ``is_first`` rows (an LSTM
  zeroes ``h``/``c`` before the step; an RSSM zeroes the deterministic
  state after the GRU advance, exactly as its trainer does), so env
  runners never special-case per family;
- a matching jittable sequence forward for the learner (e.g.
  ``jax_lstm_forward_seq``) that re-applies the same resets inside one
  ``lax.scan`` over the window, with the carried state injected at the
  window start (burn-in-free).

Env runners record the PRE-step carried state per step (``state_in``
columns) plus the ``is_first`` flag; sequence windows then ship the
recorded state at window starts and replay resets from the flags.
Module families are detected by marker keys in the one shared param
pytree: ``lstm_wx`` (LSTM policy), ``gru_x_w`` (RSSM acting tower),
``mu_w`` (continuous squashed-Gaussian), else feedforward-discrete —
so dispatch needs no per-algorithm branching anywhere.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

Params = Dict[str, np.ndarray]


def init_policy_params(obs_size: int, num_actions: int,
                       hidden: Tuple[int, ...] = (64, 64),
                       seed: int = 0) -> Params:
    rng = np.random.default_rng(seed)
    params: Params = {}
    sizes = (obs_size,) + hidden

    def dense(name, fan_in, fan_out, scale):
        params[f"{name}_w"] = (rng.standard_normal((fan_in, fan_out))
                               * scale).astype(np.float32)
        params[f"{name}_b"] = np.zeros(fan_out, np.float32)

    for tower in ("p", "v"):
        for i in range(len(hidden)):
            dense(f"{tower}{i}", sizes[i], sizes[i + 1],
                  np.sqrt(2.0 / sizes[i]))
    # small-init policy head → near-uniform initial policy
    dense("pi", sizes[-1], num_actions, 0.01)
    dense("vh", sizes[-1], 1, np.sqrt(1.0 / sizes[-1]))
    return params


def _n_hidden(params) -> int:
    n = 0
    while f"p{n}_w" in params:
        n += 1
    return n


def np_forward(params: Params, obs: np.ndarray):
    """(B, obs) → (logits (B, A), value (B,)). Pure numpy (env runners)."""
    x = v = obs
    for i in range(_n_hidden(params)):
        x = np.tanh(x @ params[f"p{i}_w"] + params[f"p{i}_b"])
        v = np.tanh(v @ params[f"v{i}_w"] + params[f"v{i}_b"])
    logits = x @ params["pi_w"] + params["pi_b"]
    value = (v @ params["vh_w"] + params["vh_b"])[:, 0]
    return logits, value


def jax_forward(params, obs):
    """Same network in jnp (learners); params may be jax arrays."""
    import jax.numpy as jnp

    x = v = obs
    for i in range(_n_hidden(params)):
        x = jnp.tanh(x @ params[f"p{i}_w"] + params[f"p{i}_b"])
        v = jnp.tanh(v @ params[f"v{i}_w"] + params[f"v{i}_b"])
    logits = x @ params["pi_w"] + params["pi_b"]
    value = (v @ params["vh_w"] + params["vh_b"])[:, 0]
    return logits, value


def np_sample_action(params: Params, obs: np.ndarray,
                     rng: np.random.Generator):
    """Single-obs categorical sample → (action, logp, value)."""
    logits, value = np_forward(params, obs[None])
    logits = logits[0] - logits[0].max()
    p = np.exp(logits)
    p /= p.sum()
    action = int(rng.choice(len(p), p=p))
    return action, float(np.log(p[action] + 1e-20)), float(value[0])


def _np_categorical_sample(p: np.ndarray, rng: np.random.Generator):
    """Vectorized categorical draw over probs (..., K) → (idx (...,),
    logp (...,)). Gumbel-max: one vectorized draw instead of N
    rng.choice calls."""
    g = rng.gumbel(size=p.shape)
    idx = (np.log(p + 1e-20) + g).argmax(axis=-1)
    logp = np.log(np.take_along_axis(
        p, idx[..., None], axis=-1)[..., 0] + 1e-20)
    return idx, logp


def np_sample_actions_batch(params: Params, obs: np.ndarray,
                            rng: np.random.Generator):
    """Vectorized categorical sample over a batch of observations:
    (N, obs) → (actions (N,), logps (N,), values (N,)). One forward matmul
    for the whole env vector — the point of vectorized env runners
    (reference rllib/env/vector/)."""
    logits, values = np_forward(params, obs)
    actions, logps = _np_categorical_sample(
        _np_softmax(logits, axis=1), rng)
    return actions.astype(np.int32), logps.astype(np.float32), \
        values.astype(np.float32)


# ----------------------------------------------------------- continuous
# Tanh-squashed Gaussian policy (SAC-style, reference
# rllib/algorithms/sac/sac_learner.py + torch squashed-gaussian dist):
# trunk "c{i}" -> heads "mu" and "ls" (state-dependent log-std), plus
# "action_scale" bounds. Detected by `"mu_w" in params` — env runners
# dispatch on it with no per-algorithm branching.

LOGSTD_MIN, LOGSTD_MAX = -5.0, 2.0


def init_continuous_policy_params(obs_size: int, action_dim: int,
                                  hidden: Tuple[int, ...] = (64, 64),
                                  seed: int = 0,
                                  action_scale: float = 1.0) -> Params:
    rng = np.random.default_rng(seed)
    params: Params = {}
    sizes = (obs_size,) + hidden

    def dense(name, fan_in, fan_out, scale):
        params[f"{name}_w"] = (rng.standard_normal((fan_in, fan_out))
                               * scale).astype(np.float32)
        params[f"{name}_b"] = np.zeros(fan_out, np.float32)

    for i in range(len(hidden)):
        dense(f"c{i}", sizes[i], sizes[i + 1], np.sqrt(2.0 / sizes[i]))
    dense("mu", sizes[-1], action_dim, 0.01)
    dense("ls", sizes[-1], action_dim, 0.01)
    params["action_scale"] = np.asarray(action_scale, np.float32)
    return params


def _n_cont_hidden(params) -> int:
    n = 0
    while f"c{n}_w" in params:
        n += 1
    return n


def np_continuous_dist(params: Params, obs: np.ndarray):
    """(B, obs) → (mu (B, A), std (B, A)) of the pre-squash Gaussian."""
    x = obs
    for i in range(_n_cont_hidden(params)):
        x = np.tanh(x @ params[f"c{i}_w"] + params[f"c{i}_b"])
    mu = x @ params["mu_w"] + params["mu_b"]
    logstd = np.clip(x @ params["ls_w"] + params["ls_b"],
                     LOGSTD_MIN, LOGSTD_MAX)
    return mu, np.exp(logstd)


def np_sample_continuous_batch(params: Params, obs: np.ndarray,
                               rng: np.random.Generator):
    """(N, obs) → (actions (N, A) f32, logps (N,), values zeros (N,)).
    Values are zeros: off-policy consumers (SAC) bootstrap from their own
    critics, not runner-side value estimates."""
    mu, std = np_continuous_dist(params, obs)
    eps = rng.standard_normal(mu.shape)
    pre = mu + std * eps
    scale = float(params["action_scale"])
    act = np.tanh(pre) * scale
    logp = (-0.5 * (eps ** 2 + np.log(2 * np.pi)) - np.log(std)
            - np.log(scale * (1 - np.tanh(pre) ** 2) + 1e-6)).sum(axis=1)
    return (act.astype(np.float32), logp.astype(np.float32),
            np.zeros(len(obs), np.float32))


def is_continuous(params: Params) -> bool:
    return "mu_w" in params


def action_spec(params: Params):
    """(trailing action shape, dtype) a runner should buffer for."""
    if is_continuous(params):
        return (params["mu_b"].shape[0],), np.float32
    return (), np.int32


# ------------------------------------------------------------- stateful
# Recurrent policy schema (see module docstring). Two families:
#
# - LSTM policy ("lstm_wx" marker): TWIN recurrent towers — obs
#   embedding -> LSTM cell -> head, separately for policy and value
#   (same twin-tower rationale as the MLP above: a shared trunk lets the
#   value regression's large early losses dominate the recurrent
#   features and stall policy learning at this scale — observed as a
#   flat return curve).  State: {"h","c"} policy tower, {"hv","cv"}
#   value tower, each (B, H).
# - RSSM acting tower ("gru_x_w" marker): the inference-only slice of a
#   DreamerV3 world model (GRU advance + posterior + actor), shipped by
#   rl/dreamerv3.py so env runners act on the TRUE latent.
#   State: {"h": (B, H), "z": (B, Z), "a": (B, A) one-hot prev action}.


def is_stateful(params: Params) -> bool:
    return "lstm_wx" in params or "gru_x_w" in params


def init_lstm_policy_params(obs_size: int, num_actions: int,
                            hidden: int = 64, seed: int = 0) -> Params:
    rng = np.random.default_rng(seed)
    params: Params = {}

    def dense(name, fan_in, fan_out, scale):
        params[f"{name}_w"] = (rng.standard_normal((fan_in, fan_out))
                               * scale).astype(np.float32)
        params[f"{name}_b"] = np.zeros(fan_out, np.float32)

    def lstm(prefix):
        params[f"{prefix}wx"] = (
            rng.standard_normal((hidden, 4 * hidden))
            * np.sqrt(1.0 / hidden)).astype(np.float32)
        params[f"{prefix}wh"] = (
            rng.standard_normal((hidden, 4 * hidden))
            * np.sqrt(1.0 / hidden)).astype(np.float32)
        b = np.zeros(4 * hidden, np.float32)
        b[hidden:2 * hidden] = 1.0      # forget-gate bias: remember early
        params[f"{prefix}b"] = b

    dense("emb", obs_size, hidden, np.sqrt(2.0 / obs_size))
    lstm("lstm_")                       # policy tower (family marker)
    dense("vemb", obs_size, hidden, np.sqrt(2.0 / obs_size))
    lstm("lstm_v_")                     # value tower
    # small-init policy head → near-uniform initial policy (as above)
    dense("pi", hidden, num_actions, 0.01)
    dense("vh", hidden, 1, np.sqrt(1.0 / hidden))
    return params


def get_initial_state(params: Params, batch_size: int = 1
                      ) -> Dict[str, np.ndarray]:
    """Zero state sized for ``batch_size`` envs; ``{}`` if feedforward."""
    if "lstm_wx" in params:
        H = params["lstm_wh"].shape[0]
        z = np.zeros((batch_size, H), np.float32)
        return {"h": z, "c": z.copy(), "hv": z.copy(), "cv": z.copy()}
    if "gru_x_w" in params:
        H = params["gru_h_w"].shape[0]
        Z = params["post_logits_w"].shape[1]
        A = params["actor_logits_w"].shape[1]
        return {"h": np.zeros((batch_size, H), np.float32),
                "z": np.zeros((batch_size, Z), np.float32),
                "a": np.zeros((batch_size, A), np.float32)}
    return {}


def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_lstm_cell(wx, wh, b, x, h, c):
    H = h.shape[1]
    z = x @ wx + h @ wh + b
    i = _np_sigmoid(z[:, :H])
    f = _np_sigmoid(z[:, H:2 * H])
    g = np.tanh(z[:, 2 * H:3 * H])
    o = _np_sigmoid(z[:, 3 * H:])
    c2 = f * c + i * g
    return o * np.tanh(c2), c2


def np_lstm_step(params: Params, obs: np.ndarray,
                 state: Dict[str, np.ndarray], is_first: np.ndarray):
    """One batched twin-tower LSTM step: (B, obs) → (logits, values,
    next_state). Rows flagged ``is_first`` restart from zero state
    BEFORE the step."""
    first = np.asarray(is_first, bool)[:, None]

    def tower(emb, prefix, hk, ck):
        h = np.where(first, 0.0, state[hk]).astype(np.float32)
        c = np.where(first, 0.0, state[ck]).astype(np.float32)
        x = np.tanh(obs @ params[f"{emb}_w"] + params[f"{emb}_b"])
        return _np_lstm_cell(params[f"{prefix}wx"], params[f"{prefix}wh"],
                             params[f"{prefix}b"], x, h, c)

    hp, cp = tower("emb", "lstm_", "h", "c")
    hv, cv = tower("vemb", "lstm_v_", "hv", "cv")
    logits = hp @ params["pi_w"] + params["pi_b"]
    values = (hv @ params["vh_w"] + params["vh_b"])[:, 0]
    return (logits, values.astype(np.float32),
            {"h": hp.astype(np.float32), "c": cp.astype(np.float32),
             "hv": hv.astype(np.float32), "cv": cv.astype(np.float32)})


def jax_lstm_step(params, obs, state, is_first):
    """The same twin-tower cell in jnp (single step; used by the scan).
    ``state`` is a dict {"h","c","hv","cv"} of (B, H) arrays."""
    import jax
    import jax.numpy as jnp

    first = is_first[:, None]
    H = params["lstm_wh"].shape[0]

    def cell(wx, wh, b, x, h, c):
        z = x @ wx + h @ wh + b
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H:2 * H])
        g = jnp.tanh(z[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H:])
        c2 = f * c + i * g
        return o * jnp.tanh(c2), c2

    def tower(emb, prefix, hk, ck):
        h = jnp.where(first, 0.0, state[hk])
        c = jnp.where(first, 0.0, state[ck])
        x = jnp.tanh(obs @ params[f"{emb}_w"] + params[f"{emb}_b"])
        return cell(params[f"{prefix}wx"], params[f"{prefix}wh"],
                    params[f"{prefix}b"], x, h, c)

    hp, cp = tower("emb", "lstm_", "h", "c")
    hv, cv = tower("vemb", "lstm_v_", "hv", "cv")
    logits = hp @ params["pi_w"] + params["pi_b"]
    values = (hv @ params["vh_w"] + params["vh_b"])[:, 0]
    return logits, values, {"h": hp, "c": cp, "hv": hv, "cv": cv}


def jax_lstm_forward_seq(params, obs, state, is_first):
    """Learner-side sequence forward: (B, L, obs) + injected window-start
    state dict → (logits (B, L, A), values (B, L)) under ONE ``lax.scan``
    over L, re-applying the acting-time ``is_first`` resets mid-window."""
    import jax

    def step(carry, xs):
        o_t, first_t = xs
        logits, values, carry2 = jax_lstm_step(params, o_t, carry, first_t)
        return carry2, (logits, values)

    xs = (obs.swapaxes(0, 1), is_first.swapaxes(0, 1))
    _, (logits, values) = jax.lax.scan(step, dict(state), xs)
    return logits.swapaxes(0, 1), values.swapaxes(0, 1)


# -------- RSSM acting tower (numpy mirror of DreamerV3Learner's model)

def _np_symlog(x):
    return np.sign(x) * np.log1p(np.abs(x))


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def np_rssm_advance(params: Params, obs: np.ndarray,
                    state: Dict[str, np.ndarray], is_first: np.ndarray):
    """Deterministic half of the RSSM acting step: GRU advance on
    (h, z_prev, a_prev), post-advance ``is_first`` reset (matching the
    trainer, which zeroes h AFTER the GRU), then posterior logits with
    1% unimix over the symlog'd observation. Returns (h2, post_probs
    (B, cats, classes))."""
    h, z, a = state["h"], state["z"], state["a"]
    D = params["gru_h_w"].shape[0]
    meta = params["rssm_meta"]
    cats, classes = int(meta[0]), int(meta[1])
    x = np.concatenate([z, a], axis=-1)
    gx = x @ params["gru_x_w"] + params["gru_x_b"]
    gh = h @ params["gru_h_w"] + params["gru_h_b"]
    r = _np_sigmoid(gx[:, :D] + gh[:, :D])
    u = _np_sigmoid(gx[:, D:2 * D] + gh[:, D:2 * D])
    cand = np.tanh(gx[:, 2 * D:] + r * gh[:, 2 * D:])
    h2 = u * cand + (1.0 - u) * h
    h2 = np.where(np.asarray(is_first, bool)[:, None], 0.0, h2)
    e = np.tanh(_np_symlog(obs) @ params["enc0_w"] + params["enc0_b"])
    pl = (np.tanh(np.concatenate([h2, e], -1) @ params["post0_w"]
                  + params["post0_b"])
          @ params["post_logits_w"] + params["post_logits_b"])
    probs = _np_softmax(pl.reshape(len(obs), cats, classes), -1)
    probs = 0.99 * probs + 0.01 / classes
    return h2.astype(np.float32), probs


def _np_rssm_sample_batch(params: Params, obs: np.ndarray,
                          state: Dict[str, np.ndarray],
                          is_first: np.ndarray, rng: np.random.Generator):
    B = len(obs)
    A = params["actor_logits_w"].shape[1]
    h2, post = np_rssm_advance(params, obs, state, is_first)
    cats, classes = post.shape[1], post.shape[2]
    idx, _ = _np_categorical_sample(post, rng)   # per-categorical draw
    z2 = np.eye(classes, dtype=np.float32)[idx].reshape(
        B, cats * classes)
    alog = (np.tanh(np.concatenate([h2, z2], -1) @ params["actor0_w"]
                    + params["actor0_b"])
            @ params["actor_logits_w"] + params["actor_logits_b"])
    ap = 0.99 * _np_softmax(alog, -1) + 0.01 / A   # trainer's action unimix
    actions, logps = _np_categorical_sample(ap, rng)
    a2 = np.eye(A, dtype=np.float32)[actions]
    # values are zeros: the Dreamer critic lives in imagination, runners
    # never estimate values (same contract as the continuous sampler)
    return (actions.astype(np.int32), logps.astype(np.float32),
            np.zeros(B, np.float32),
            {"h": h2, "z": z2, "a": a2})


def np_stateful_sample_batch(params: Params, obs: np.ndarray,
                             state: Dict[str, np.ndarray],
                             is_first: np.ndarray,
                             rng: np.random.Generator):
    """Vectorized stateful acting step: (N, obs) + carried state →
    (actions (N,), logps (N,), values (N,), next_state). Dispatches on
    the module family's marker key; each family applies its own
    ``is_first`` reset semantics internally."""
    if "gru_x_w" in params:
        return _np_rssm_sample_batch(params, obs, state, is_first, rng)
    logits, values, next_state = np_lstm_step(params, obs, state, is_first)
    actions, logps = _np_categorical_sample(_np_softmax(logits, -1), rng)
    return (actions.astype(np.int32), logps.astype(np.float32),
            values, next_state)


def np_stateful_values(params: Params, obs: np.ndarray,
                       state: Dict[str, np.ndarray],
                       is_first: np.ndarray) -> np.ndarray:
    """Value estimates WITHOUT advancing the carried state (bootstrap at
    fragment ends). RSSM runners return zeros (no runner-side critic)."""
    if "gru_x_w" in params:
        return np.zeros(len(obs), np.float32)
    _, values, _ = np_lstm_step(params, obs, state, is_first)
    return values
