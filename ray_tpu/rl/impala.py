"""IMPALA: asynchronous sample/learn with aggregator actors and V-trace.

Reference: ``rllib/algorithms/impala/impala.py:599`` (async training_step)
and ``:634-650`` (aggregator actors building train batches from episode
refs ahead of the learner). Architecture here:

- EnvRunner actors sample continuously; the driver keeps one ``sample()``
  call in flight per runner and NEVER blocks the learner on sampling.
- Completed fragment REFS are handed to :class:`Aggregator` actors (the
  fragment bytes flow runner→aggregator through the object plane, not
  through the driver), which concatenate fragments into train batches.
- The learner applies **V-trace** off-policy correction (Espeholt et al.,
  2018): sampling continues under stale weights, and the clipped
  importance-sampling scan (a ``lax.scan`` over the fragment, reversed)
  corrects the value targets and policy-gradient advantages.

TPU note: the learner update is one jitted function of fixed-shape batches;
on a TPU learner the same function pjit-s over a mesh unchanged.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, _probe_env
from ray_tpu.rl.module import init_policy_params, jax_forward


class Aggregator:
    """Batch-building actor (reference impala.py:634 aggregator actors):
    receives rollout fragments (by ref — the data plane bypasses the
    driver), concatenates them into fixed train batches."""

    def __init__(self, train_batch_size: int):
        import threading

        self._size = train_batch_size
        self._buffer: List[Dict[str, np.ndarray]] = []
        self._steps = 0
        # max_concurrency > 1 runs these sync methods on multiple threads
        self._lock = threading.Lock()

    def add_fragment(self, fragment) -> int:
        # vectorized runners ship a LIST of per-env fragments in one call
        frags = fragment if isinstance(fragment, list) else [fragment]
        with self._lock:
            for f in frags:
                self._buffer.append(f)
                self._steps += len(f["obs"])
            return self._steps

    def get_ready_batch(self) -> Optional[Dict[str, Any]]:
        """A concatenated batch of >= train_batch_size steps, else None."""
        with self._lock:
            if self._steps < self._size:
                return None
            frags, self._buffer = self._buffer, []
            self._steps = 0
        keys = ("obs", "actions", "logp", "rewards", "values", "dones")
        batch = {k: np.concatenate([f[k] for f in frags]) for k in keys}
        # fragment boundaries never propagate values across: mark the last
        # step of each fragment with its bootstrap value
        bootstrap = np.zeros(len(batch["obs"]), np.float32)
        is_last = np.zeros(len(batch["obs"]), bool)
        off = 0
        for f in frags:
            n = len(f["obs"])
            bootstrap[off + n - 1] = f["last_value"]
            is_last[off + n - 1] = True
            off += n
        batch["bootstrap_value"] = bootstrap
        batch["fragment_end"] = is_last
        batch["episode_returns"] = np.asarray(
            [r for f in frags for r in f["episode_returns"]], np.float32)
        return batch


def vtrace_corrections(values, batch, rho, *, gamma, rho_bar, c_bar):
    """V-trace (Espeholt et al., 2018) value targets + pg advantages over
    a batch of concatenated fragments. The reverse-scan carry zeroes at
    fragment boundaries: concatenated fragments come from unrelated
    trajectories, so corr_{t+1} of the NEXT fragment must not leak into
    this fragment's targets. Returns (vs, pg_adv); callers stop-gradient
    `rho` themselves. Shared by IMPALA and APPO losses."""
    import jax
    import jax.numpy as jnp

    values_sg = jax.lax.stop_gradient(values)
    nonterm = 1.0 - batch["dones"].astype(jnp.float32)
    # next-step values: train-time values shifted left; fragment tails
    # use the runner's bootstrap value
    next_values = jnp.where(batch["fragment_end"],
                            batch["bootstrap_value"],
                            jnp.roll(values_sg, -1))
    frag_end = batch["fragment_end"].astype(jnp.float32)
    rho_c = jnp.minimum(rho_bar, rho)
    c = jnp.minimum(c_bar, rho)
    delta = rho_c * (batch["rewards"] + gamma * nonterm * next_values
                     - values_sg)

    def body(acc, xs):
        d, c_t, nt, fe = xs
        acc = jnp.where(fe, 0.0, acc)   # cut across fragments
        acc = d + gamma * nt * c_t * acc
        return acc, acc

    _, corr = jax.lax.scan(body, jnp.zeros(()),
                           (delta, c, nonterm, frag_end), reverse=True)
    vs = values_sg + corr
    vs_next = jnp.where(batch["fragment_end"],
                        batch["bootstrap_value"], jnp.roll(vs, -1))
    pg_adv = rho_c * (batch["rewards"] + gamma * nonterm * vs_next
                      - values_sg)
    return vs, pg_adv


class IMPALALearner:
    """Policy gradient with V-trace targets (reference: rllib vtrace)."""

    def __init__(self, params, *, lr: float = 5e-4, gamma: float = 0.99,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                 rho_bar: float = 1.0, c_bar: float = 1.0,
                 grad_clip: float = 40.0):
        import jax
        import optax

        self.gamma = gamma
        self._optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip), optax.adam(lr))
        self._params = jax.tree.map(jax.numpy.asarray, dict(params))
        self._opt_state = self._optimizer.init(self._params)
        self._step = self._build_step(gamma, vf_coeff, entropy_coeff,
                                      rho_bar, c_bar)
        self.updates = 0

    def _make_loss_fn(self, gamma, vf_c, ent_c, rho_bar, c_bar):
        """Loss hook: APPO overrides ONLY this (reference structure:
        appo_learner.py subclasses the IMPALA learner, swapping the
        surrogate while sharing v-trace and the update scaffolding)."""
        import jax
        import jax.numpy as jnp

        def loss_fn(params, batch):
            logits, values = jax_forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None].astype(jnp.int32),
                axis=1)[:, 0]
            rho = jnp.exp(logp - batch["logp"])
            rho = jax.lax.stop_gradient(rho)
            nonterm = 1.0 - batch["dones"].astype(jnp.float32)
            vs, pg_adv = vtrace_corrections(
                values, batch, rho, gamma=gamma, rho_bar=rho_bar,
                c_bar=c_bar)
            pi_loss = -jnp.mean(logp * pg_adv)
            vf_loss = jnp.mean((values - vs) ** 2)
            entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
            total = pi_loss + vf_c * vf_loss - ent_c * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "mean_rho": jnp.mean(rho)}

        return loss_fn

    def _build_step(self, gamma, vf_c, ent_c, rho_bar, c_bar):
        import jax
        import optax

        optimizer = self._optimizer
        loss_fn = self._make_loss_fn(gamma, vf_c, ent_c, rho_bar, c_bar)

        def step(params, opt_state, batch):
            (total, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = total
            return params, opt_state, aux

        # Split pair for LearnerGroup gradient sync (reference Learner API:
        # compute_gradients:464 / apply_gradients:607).
        def grad(params, batch):
            (total, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            aux["total_loss"] = total
            return grads, aux

        def apply(params, opt_state, grads):
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._grad_fn = jax.jit(grad)
        self._apply_fn = jax.jit(apply, donate_argnums=(0, 1))
        return jax.jit(step, donate_argnums=(0, 1))

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k != "episode_returns"}
        self._params, self._opt_state, aux = self._step(
            self._params, self._opt_state, jb)
        self.updates += 1
        return {k: float(v) for k, v in aux.items()}

    def compute_gradients(self, batch: Dict[str, np.ndarray]):
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k != "episode_returns"}
        return self._grad_fn(self._params, jb)

    def apply_gradients(self, grads) -> None:
        self._params, self._opt_state = self._apply_fn(
            self._params, self._opt_state, grads)
        self.updates += 1

    def set_weights(self, params: Dict[str, np.ndarray]):
        import jax

        self._params = jax.tree.map(jax.numpy.asarray, dict(params))

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self._params.items()}


@dataclasses.dataclass
class IMPALAConfig(AlgorithmConfig):
    train_batch_size: int = 512
    num_aggregators: int = 1
    lr: float = 5e-4
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    # max learner updates pulled per training_step() call
    max_updates_per_step: int = 8
    broadcast_interval: int = 1  # weight push every N learner updates
    # >1 → LearnerGroup: N learner actors, batch sharded across them,
    # gradients mean-allreduced per update (reference learner_group.py:100).
    # "kv" syncs over the GCS KV store (CPU hosts); "xla" over ICI meshes.
    num_learners: int = 1
    learner_backend: str = "kv"
    # async update pipeline depth per LearnerGroup (IMPALA's update queue)
    max_inflight_updates: int = 4

    @property
    def algo_class(self):
        return IMPALA

    # learner construction hooks so APPO reuses the whole async driver
    # with a different loss (reference: APPO subclasses IMPALA,
    # rllib/algorithms/appo/appo.py:40)
    def learner_cls(self):
        return IMPALALearner

    def learner_kwargs(self) -> dict:
        return dict(lr=self.lr, gamma=self.gamma, vf_coeff=self.vf_coeff,
                    entropy_coeff=self.entropy_coeff)


class IMPALA(Algorithm):
    """Async IMPALA driver (reference impala.py:599 training_step)."""

    def __init__(self, config: IMPALAConfig):
        import ray_tpu

        super().__init__(config)
        params = init_policy_params(
            self._env_probe["obs_size"], self._env_probe["num_actions"],
            hidden=tuple(config.hidden), seed=config.seed)
        self.learner = None
        self.learner_group = None
        self._learner_updates = 0
        learner_cls = config.learner_cls()
        learner_kwargs = config.learner_kwargs()
        if config.num_learners > 1:
            from ray_tpu.rl.learner_group import LearnerGroup

            def factory(_p=params, _cls=learner_cls, _kw=learner_kwargs):
                return _cls(_p, **_kw)

            self.learner_group = LearnerGroup(
                factory, num_learners=config.num_learners,
                backend=config.learner_backend,
                max_inflight_updates=config.max_inflight_updates)
        else:
            self.learner = learner_cls(params, **learner_kwargs)
        agg_cls = ray_tpu.remote(Aggregator)
        self._aggregators = [
            agg_cls.options(max_concurrency=4).remote(config.train_batch_size)
            for _ in range(config.num_aggregators)]
        self._agg_rr = 0
        self._inflight: Dict[Any, int] = {}   # sample ref -> runner index
        self._steps_sampled = 0
        self._steps_trained = 0
        self._push_weights()
        self._kick_all_runners()

    # ------------------------------------------------------------ async loop
    def get_weights(self):
        if self.learner_group is not None:
            return self.learner_group.get_weights()
        return self.learner.get_weights()

    @property
    def _num_learner_updates(self) -> int:
        if self.learner_group is not None:
            return self._learner_updates
        return self.learner.updates

    def _push_weights(self):
        self._weights_version += 1
        weights = self.get_weights()
        self.env_runner_group.foreach_actor(
            lambda a: a.set_weights.remote(weights, self._weights_version))

    def _kick_all_runners(self):
        actors = self.env_runner_group.actors
        for idx in self.env_runner_group.healthy_actor_ids():
            if not any(i == idx for i in self._inflight.values()):
                self._kick_runner(idx, actors[idx])

    def _kick_runner(self, idx, actor):
        ref = actor.sample.remote(self.config.rollout_fragment_length)
        self._inflight[ref] = idx

    def _route_completed_samples(self, timeout: float):
        """Move finished fragments runner→aggregator and resample; the
        learner never waits on any individual runner."""
        import ray_tpu

        if not self._inflight:
            self._kick_all_runners()
            if not self._inflight:
                raise RuntimeError("no healthy env runners")
        ready, _ = ray_tpu.wait(list(self._inflight),
                                num_returns=1, timeout=timeout)
        for ref in ready:
            idx = self._inflight.pop(ref)
            agg = self._aggregators[self._agg_rr % len(self._aggregators)]
            self._agg_rr += 1
            # fragment bytes travel runner→aggregator via the ref
            agg.add_fragment.remote(ref)
            self._steps_sampled += (
                self.config.rollout_fragment_length
                * getattr(self.config, "num_envs_per_env_runner", 1))
            if idx in self.env_runner_group.healthy_actor_ids():
                self._kick_runner(idx, self.env_runner_group.actors[idx])

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        self._maybe_restore_runners()
        updates = 0
        metrics: Dict[str, float] = {}
        returns: List[float] = []
        deadline = time.monotonic() + 30.0
        while updates < self.config.max_updates_per_step \
                and time.monotonic() < deadline:
            self._route_completed_samples(timeout=0.05)
            got_batch = False
            for agg in self._aggregators:
                batch = ray_tpu.get(agg.get_ready_batch.remote(), timeout=60)
                if batch is None:
                    continue
                got_batch = True
                if self.learner_group is not None:
                    # async update queue (reference impala.py:599): enqueue
                    # without waiting; drain whatever finished. A full
                    # pipeline drops the batch (classic IMPALA backpressure).
                    if self.learner_group.async_update(batch):
                        self._steps_trained += len(batch["obs"])
                        returns.extend(batch["episode_returns"].tolist())
                    for m in self.learner_group.poll_updates():
                        metrics = m
                        updates += 1
                        self._learner_updates += 1
                        if self._learner_updates \
                                % self.config.broadcast_interval == 0:
                            self._push_weights()
                else:
                    metrics = self.learner.update(batch)
                    self._steps_trained += len(batch["obs"])
                    returns.extend(batch["episode_returns"].tolist())
                    updates += 1
                    if self.learner.updates \
                            % self.config.broadcast_interval == 0:
                        self._push_weights()
            if not got_batch:
                continue  # keep routing samples; learner stays decoupled
        self._return_window = (self._return_window
                               + [float(r) for r in returns])[-100:]
        return {
            "env_runners": {
                "episode_return_mean": self.episode_return_mean(),
                "num_episodes": len(returns),
                "num_env_steps_sampled": self._steps_sampled,
                "num_healthy_workers":
                    self.env_runner_group.num_healthy_actors(),
            },
            "learners": {"default_policy": dict(
                metrics, num_updates=self._num_learner_updates,
                num_env_steps_trained=self._steps_trained)},
        }
