"""Mixture-of-Experts decoder (Mixtral-style), TPU-first expert parallelism.

The reference framework has no in-tree MoE — its LLM stack delegates to
vLLM (reference ``python/ray/llm/_internal/serve/deployments/llm/vllm/``),
and its EP story is torch process groups. Here expert parallelism is
GSPMD-native (the design the public MoE-on-TPU literature converged on —
GShard/Switch):

- Experts are one stacked weight tensor with a leading ``expert`` logical
  axis, sharded over the mesh's ep axes by the rule table
  (``parallel/sharding.py: expert``). No per-expert modules, no manual
  all-to-all: the dispatch einsum ``tec,th->ech`` contracts a
  token-sharded activation against a token-routed one-hot into an
  EXPERT-sharded tensor, and XLA lowers the resharding to ICI all-to-all.
- Routing is top-k softmax gating with static expert capacity
  (``capacity_factor``) so every shape is static under jit: dropped
  tokens (over capacity) pass through the residual stream untouched.
- The Switch load-balancing auxiliary loss and a router z-loss keep the
  gate from collapsing; both are collected through the layer scan.
- Attention/norms/rope reuse the Llama components, so sp (ring attention)
  and tp compose with ep via the same rule table.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models import llama
from ray_tpu.ops.norms import rmsnorm
from ray_tpu.ops.rope import rope_frequencies
from ray_tpu.parallel.sharding import ShardingRules, with_logical_constraint

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    base: llama.LlamaConfig = dataclasses.field(
        default_factory=lambda: llama.CONFIGS["tiny"])
    n_experts: int = 8
    top_k: int = 2
    # per-expert slots = ceil(top_k * tokens / n_experts * capacity_factor)
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    router_z_coef: float = 1e-3

    def capacity(self, tokens: int) -> int:
        return max(1, math.ceil(
            self.top_k * tokens * self.capacity_factor / self.n_experts))

    def num_params(self) -> int:
        c = self.base
        dense = llama.LlamaConfig.num_params(
            dataclasses.replace(c, mlp_dim=0))
        experts = self.n_experts * 3 * c.hidden * c.mlp_dim * c.n_layers
        router = c.hidden * self.n_experts * c.n_layers
        return dense + experts + router

    def active_params(self) -> int:
        """Params touched per token (what FLOPs scale with)."""
        c = self.base
        dense = llama.LlamaConfig.num_params(
            dataclasses.replace(c, mlp_dim=0))
        experts = self.top_k * 3 * c.hidden * c.mlp_dim * c.n_layers
        router = c.hidden * self.n_experts * c.n_layers
        return dense + experts + router

    def flops_per_token(self, seq: Optional[int] = None) -> float:
        c = self.base
        seq = c.max_seq if seq is None else seq
        return 6.0 * self.active_params() + 6.0 * c.n_layers * seq * c.q_dim


CONFIGS: Dict[str, MoEConfig] = {
    "debug": MoEConfig(base=llama.CONFIGS["debug"], n_experts=4, top_k=2),
    "tiny": MoEConfig(base=llama.CONFIGS["tiny"], n_experts=8, top_k=2),
    # Mixtral-8x7B-ish shapes on the Llama-8B backbone
    "8x7b": MoEConfig(base=dataclasses.replace(
        llama.CONFIGS["8b"], hidden=4096, n_layers=32, mlp_dim=14336),
        n_experts=8, top_k=2),
}


def param_logical_axes(config: MoEConfig) -> Params:
    axes = llama.param_logical_axes(config.base)
    layer_axes = dict(axes["layers"])
    for name in ("w_gate", "w_up", "w_down"):
        layer_axes.pop(name)
    layer_axes.update({
        "router": ("layers", "embed", None),  # tiny; replicated
        "we_gate": ("layers", "expert", "embed_fsdp", "mlp"),
        "we_up": ("layers", "expert", "embed_fsdp", "mlp"),
        "we_down": ("layers", "expert", "mlp", "embed_fsdp"),
    })
    axes["layers"] = layer_axes
    return axes


def init_params(config: MoEConfig, key: jax.Array) -> Params:
    c = config.base
    params = llama.init_params(c, key)
    layers = dict(params["layers"])
    for name in ("w_gate", "w_up", "w_down"):
        layers.pop(name)
    k = iter(jax.random.split(jax.random.fold_in(key, 7), 8))
    std = c.hidden ** -0.5
    out_std = std / (2 * c.n_layers) ** 0.5
    dt = c.dtype
    L, E = c.n_layers, config.n_experts

    def tn(key, shape, s):
        return (jax.random.truncated_normal(key, -3, 3, shape, jnp.float32)
                * s).astype(dt)

    # the router runs in f32: tiny matmul, and gate ordering is precision-
    # sensitive (bf16 ties reshuffle top-k between devices)
    layers["router"] = tn(next(k), (L, c.hidden, E), std).astype(jnp.float32)
    layers["we_gate"] = tn(next(k), (L, E, c.hidden, c.mlp_dim), std)
    layers["we_up"] = tn(next(k), (L, E, c.hidden, c.mlp_dim), std)
    layers["we_down"] = tn(next(k), (L, E, c.mlp_dim, c.hidden), out_std)
    params["layers"] = layers
    return params


def _moe_mlp(x: jax.Array, layer: Params, config: MoEConfig,
             rules: ShardingRules) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Top-k routed expert FFN with static capacity.

    x: (B, S, H) → (B, S, H), plus router aux metrics.
    """
    c = config.base
    B, S, H = x.shape
    T = B * S
    E, K = config.n_experts, config.top_k
    C = config.capacity(T)
    # Pin the flattened token layout (the merge of batch and seq shardings):
    # without it the partitioner lets the expert-sharded layout of the
    # dispatch einsum's OUTPUT propagate backward into the per-token routing
    # tensors, then reshards their degenerate broadcast operands with
    # "involuntary full rematerialization" (seen in the 8-device dryrun).
    xt = with_logical_constraint(x.reshape(T, H), ("tokens", "embed"), rules)

    logits = jnp.einsum("th,he->te", xt.astype(jnp.float32), layer["router"])
    probs = jax.nn.softmax(logits, axis=-1)                      # (T, E)
    top_w, top_idx = jax.lax.top_k(probs, K)                     # (T, K)
    top_w = top_w / jnp.maximum(top_w.sum(-1, keepdims=True), 1e-9)

    # GShard-style slotting: earlier k-choices claim capacity first.
    dispatch = jnp.zeros((T, E, C), jnp.float32)
    combine = jnp.zeros((T, E, C), jnp.float32)
    counts = jnp.zeros((E,), jnp.int32)
    frac_dispatched = jnp.zeros((E,), jnp.float32)
    for k in range(K):  # K is a small static constant: unrolled
        mask = jax.nn.one_hot(top_idx[:, k], E, dtype=jnp.int32)  # (T, E)
        pos = counts[None, :] + jnp.cumsum(mask, axis=0) - mask   # (T, E)
        pos_t = (pos * mask).sum(-1)                              # (T,)
        kept = (pos_t < C) & (mask.sum(-1) > 0)
        counts = counts + mask.sum(0)
        slot = jax.nn.one_hot(pos_t, C, dtype=jnp.float32) \
            * kept[:, None].astype(jnp.float32)                   # (T, C)
        dispatch = dispatch + mask.astype(jnp.float32)[:, :, None] \
            * slot[:, None, :]
        # Fold the gate weight into the rank-2 slot tensor instead of
        # multiplying a (T,1,1) operand into the rank-3 product: the SPMD
        # partitioner assigns the degenerate singleton dims conflicting
        # shardings across the unrolled k-steps and falls back to
        # "involuntary full rematerialization" (seen in the 8-device dryrun).
        w_slot = slot * top_w[:, k, None]                         # (T, C)
        combine = combine + mask.astype(jnp.float32)[:, :, None] \
            * w_slot[:, None, :]
        frac_dispatched = frac_dispatched + mask.sum(0) / T

    # dispatch: token-major → expert-major; the constraint pins the expert
    # layout so XLA materializes the resharding as all-to-all over ep axes
    dispatch = with_logical_constraint(dispatch, ("tokens", None, None), rules)
    combine = with_logical_constraint(combine, ("tokens", None, None), rules)
    expert_in = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), xt)
    expert_in = with_logical_constraint(expert_in, ("expert", None, "embed"),
                                        rules)
    g = jnp.einsum("ech,ehm->ecm", expert_in, layer["we_gate"].astype(x.dtype))
    u = jnp.einsum("ech,ehm->ecm", expert_in, layer["we_up"].astype(x.dtype))
    y = jnp.einsum("ecm,emh->ech", jax.nn.silu(g) * u,
                   layer["we_down"].astype(x.dtype))
    y = with_logical_constraint(y, ("expert", None, "embed"), rules)
    out = jnp.einsum("tec,ech->th", combine.astype(x.dtype), y)

    # Switch aux loss: E * Σ_e fraction_dispatched_e · mean_prob_e — minimized
    # at uniform routing. frac counts ALL top-k assignments (pre-drop).
    mean_prob = probs.mean(0)
    aux = E * jnp.sum((frac_dispatched / K) * mean_prob)
    z = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    # fraction of (token, k) slots that fell over capacity and were dropped
    dropped = 1.0 - dispatch.sum() / (T * K)
    return out.reshape(B, S, H), {"aux": aux, "router_z": z,
                                  "dropped": dropped}


def forward(params: Params, tokens: jax.Array, config: MoEConfig,
            rules: Optional[ShardingRules] = None,
            positions: Optional[jax.Array] = None, mesh=None):
    """tokens (B, S) → (logits (B, S, V) f32, moe_metrics dict of scalars)."""
    c = config.base
    rules = rules or ShardingRules()
    tokens = with_logical_constraint(tokens, ("batch", "seq"), rules)
    table = with_logical_constraint(
        params["embed"], ("embed_vocab", "embed"), rules)
    x = table.astype(c.dtype)[tokens]
    x = with_logical_constraint(x, ("batch", "seq", "embed"), rules)
    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)

    def block(x, layer):
        h = llama._attention(rmsnorm(x, layer["attn_norm"], c.norm_eps),
                             layer, cos, sin, c, rules, positions, mesh)
        x = x + h
        x = with_logical_constraint(x, ("batch", "seq", "embed"), rules)
        h, moe_aux = _moe_mlp(rmsnorm(x, layer["mlp_norm"], c.norm_eps),
                              layer, config, rules)
        x = x + h
        x = with_logical_constraint(x, ("batch", "seq", "embed"), rules)
        return x, moe_aux

    if c.remat:
        policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                  if c.remat_policy == "dots"
                  else jax.checkpoint_policies.nothing_saveable)
        block = jax.checkpoint(block, policy=policy)
    x, aux = jax.lax.scan(block, x, params["layers"])

    x = rmsnorm(x, params["final_norm"], c.norm_eps)
    head = (params["embed"].T if c.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("bse,ev->bsv", x, head.astype(x.dtype),
                        preferred_element_type=jnp.float32)
    logits = with_logical_constraint(logits, ("batch", "seq", "vocab"), rules)
    metrics = {k: v.mean() for k, v in aux.items()}  # mean over layers
    return logits, metrics


def loss_fn(params: Params, batch: Dict[str, jax.Array], config: MoEConfig,
            rules: Optional[ShardingRules] = None, mesh=None):
    """Next-token CE + router auxiliary losses. Same contract as
    ``llama.loss_fn`` so ``training.make_train_step`` takes it unchanged."""
    tokens = batch["tokens"]
    logits, moe = forward(params, tokens, config, rules, mesh=mesh)
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    mask = batch.get("mask")
    mask = (jnp.ones_like(targets, jnp.float32) if mask is None
            else mask[:, :-1].astype(jnp.float32))
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1.0)
    ce = (nll * mask).sum() / denom
    loss = (ce + config.aux_loss_coef * moe["aux"]
            + config.router_z_coef * moe["router_z"])
    acc = ((logits.argmax(-1) == targets) * mask).sum() / denom
    return loss, {"loss": loss, "ce": ce, "accuracy": acc, "tokens": denom,
                  "aux_loss": moe["aux"], "router_z": moe["router_z"],
                  "dropped_frac": moe["dropped"]}
