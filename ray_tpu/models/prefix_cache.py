"""Radix prefix KV cache: block-level cross-request KV reuse.

The production LLM workload is shared-system-prompt traffic — thousands
of requests whose token streams agree for hundreds of tokens and diverge
at the tail. The exact-match full-prompt cache the engine used to carry
(an ``OrderedDict`` of host k/v copies) can never hit on that shape.
This module is the SGLang/vLLM answer, in-framework: a radix tree over
token-id sequences whose nodes own **ref-counted pool blocks** from
:class:`ray_tpu.models.paged_cache.BlockAllocator`.

Design points:

- **Block granularity, zero-copy sharing.** One tree node = one pool
  block = ``block_size`` tokens. Inserting a finished prompt just
  increfs the slot's existing blocks — no device traffic. A hit aliases
  the cached blocks into the new slot's table (``BlockAllocator.adopt``)
  so prefill skips them entirely; attention gathers them through the
  table like any other rows.
- **Copy-on-write at the divergence block.** When the match runs out
  mid-block (the request agrees with a cached block for its first
  ``rows`` tokens, then diverges — or simply ends inside it), the hit
  reports a COW candidate: the engine duplicates that block on device
  (``make_block_copy``) into a private block and resumes prefill at the
  exact divergence offset. The cached original stays read-only.
- **Eviction can never touch a live slot's block.** LRU eviction walks
  refcount-0 leaves only — "refcount 0" meaning no slot table references
  the block (the tree's own reference is the last one). A shared
  interior block is structurally unevictable until its whole subtree is
  gone AND every slot released it. ``check_invariants`` on the allocator
  is the chaos-test oracle for this.
- **Byte budget.** The tree holds at most ``budget_bytes`` worth of
  blocks; inserts evict LRU-first to make room and are dropped (counted,
  never raised) when every candidate is pinned by a live slot.

Host-side only, single-threaded by construction: the engine loop owns
it like it owns the allocator.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu.models.paged_cache import BlockAllocator


@dataclasses.dataclass
class PrefixMatch:
    """Result of a tree walk: ``blocks`` are fully-matched shared block
    ids covering ``len(blocks) * block_size`` tokens; ``cow`` is the
    optional divergence block — ``(block_id, rows)`` meaning the block's
    first ``rows`` tokens also match and may be reused via copy-on-write.
    ``matched`` counts every reusable token (full blocks + cow rows)."""

    blocks: List[int]
    matched: int
    cow: Optional[Tuple[int, int]] = None


class _Node:
    __slots__ = ("key", "block", "children", "parent", "last_used",
                 "tenant")

    def __init__(self, key: Tuple[int, ...], block: int,
                 parent: Optional["_Node"], tenant: Optional[str] = None):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0
        self.tenant = tenant


class RadixPrefixCache:
    """Radix tree over token-id sequences at block granularity."""

    def __init__(self, allocator: BlockAllocator, *, bytes_per_block: int,
                 budget_bytes: int):
        self._alloc = allocator
        self.block_size = allocator.page.block_size
        self.bytes_per_block = max(1, int(bytes_per_block))
        self.budget_bytes = int(budget_bytes)
        self._root = _Node((), 0, None)
        self._nodes = 0
        self._clock = 0      # monotonic LRU counter (no wall time)
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.inserted_blocks = 0
        self.evicted_blocks = 0
        self.rejected_inserts = 0
        self.cow_hits = 0
        # per-tenant cached-block attribution, for the engine's
        # cache-insert fair share (decremented on eviction)
        self.tenant_blocks: Dict[Optional[str], int] = {}

    # ------------------------------------------------------------ sizing
    @property
    def cached_blocks(self) -> int:
        return self._nodes

    def cached_bytes(self) -> int:
        return self._nodes * self.bytes_per_block

    def budget_blocks(self) -> int:
        return max(0, self.budget_bytes // self.bytes_per_block)

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_used = self._clock

    # ------------------------------------------------------------- match
    def match(self, tokens: Sequence[int]) -> PrefixMatch:
        """Longest cached prefix of ``tokens``. The caller decides how
        many tokens are eligible (the engine passes ``prompt[:-1]`` so
        the block holding the last prompt token — where decode will
        write — is always recomputed privately)."""
        bs = self.block_size
        toks = list(tokens)
        node = self._root
        blocks: List[int] = []
        i = 0
        while i + bs <= len(toks):
            key = tuple(toks[i:i + bs])
            child = node.children.get(key)
            if child is None:
                break
            self._touch(child)
            blocks.append(child.block)
            node = child
            i += bs
        # divergence: the longest partial-row agreement with any child
        cow = None
        tail = toks[i:]
        if tail:
            best_rows, best_child = 0, None
            for key, child in node.children.items():
                rows = 0
                for a, b in zip(tail, key):
                    if a != b:
                        break
                    rows += 1
                if rows > best_rows:
                    best_rows, best_child = rows, child
            if best_child is not None:
                self._touch(best_child)
                cow = (best_child.block, best_rows)
        matched = i + (cow[1] if cow else 0)
        if matched:
            self.hits += 1
            self.hit_tokens += matched
            if cow:
                self.cow_hits += 1
        else:
            self.misses += 1
        return PrefixMatch(blocks=blocks, matched=matched, cow=cow)

    # ------------------------------------------------------------ insert
    def insert(self, tokens: Sequence[int], blocks: Sequence[int],
               tenant: Optional[str] = None,
               max_new: Optional[int] = None) -> int:
        """Insert the full-block prefix of ``tokens`` whose KV lives in
        ``blocks`` (``blocks[i]`` covers tokens ``[i*bs, (i+1)*bs)`` —
        the slot's owned blocks, in table order). Existing nodes are
        reused (the physical blocks may differ between two requests that
        computed the same prefix; KV for identical token history is
        identical, so either copy serves). ``max_new`` bounds freshly
        cached blocks (the engine's per-tenant insert fair share).
        Returns new blocks cached."""
        bs = self.block_size
        toks = list(tokens)
        nfull = len(toks) // bs
        node = self._root
        # nodes on the insert path are eviction-exempt for the duration:
        # _make_room must never reclaim the node we are standing on (a
        # childless refcount-1 node from an earlier, released request)
        # or the rest of the path would graft onto a detached subtree
        path = {id(node)}
        inserted = 0
        for i in range(min(nfull, len(blocks))):
            key = tuple(toks[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is not None:
                self._touch(child)
                node = child
                path.add(id(node))
                continue
            if max_new is not None and inserted >= max_new:
                break
            if not self._make_room(1, protect=path):
                self.rejected_inserts += 1
                break
            b = int(blocks[i])
            self._alloc.ref_blocks([b])
            child = _Node(key, b, node, tenant)
            node.children[key] = child
            self._touch(child)
            path.add(id(child))
            self._nodes += 1
            inserted += 1
            self.tenant_blocks[tenant] = \
                self.tenant_blocks.get(tenant, 0) + 1
            node = child
        self.inserted_blocks += inserted
        return inserted

    # ---------------------------------------------------------- eviction
    def _evictable_leaves(self) -> List[_Node]:
        out: List[_Node] = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self._alloc.refcount(n.block) == 1:
                # tree holds the only reference: no slot table aliases
                # this block — the ONLY state eviction may reclaim
                out.append(n)
        return out

    def _evict_one(self, protect=frozenset()) -> bool:
        leaves = [n for n in self._evictable_leaves()
                  if id(n) not in protect]
        if not leaves:
            return False
        victim = min(leaves, key=lambda n: n.last_used)
        victim.parent.children.pop(victim.key, None)
        self._alloc.unref_blocks([victim.block])
        self._nodes -= 1
        self.evicted_blocks += 1
        left = self.tenant_blocks.get(victim.tenant, 1) - 1
        if left > 0:
            self.tenant_blocks[victim.tenant] = left
        else:
            self.tenant_blocks.pop(victim.tenant, None)
        return True

    def _make_room(self, nblocks: int, protect=frozenset()) -> bool:
        while self._nodes + nblocks > self.budget_blocks():
            if not self._evict_one(protect):
                return False
        return True

    def evict_for(self, nblocks: int) -> int:
        """Pool pressure: evict up to ``nblocks`` LRU unreferenced
        leaves so admission/decode growth can proceed without preempting
        a live request. Returns blocks actually returned to the pool."""
        freed = 0
        while freed < nblocks and self._evict_one():
            freed += 1
        return freed

    def clear(self) -> int:
        """Drop every node no slot references (used by tests and on
        engine teardown). Pinned nodes survive."""
        n = 0
        while self._evict_one():
            n += 1
        return n

    # ------------------------------------------------------------ digest
    def digest(self, chunk: int = 16, max_chunks: int = 8,
               cap: int = 128) -> List[int]:
        """Compact advertisement of what this tree holds: blake2b-64
        hashes of the cumulative ``chunk``-token prefixes of every
        cached path (up to ``max_chunks`` chunks deep, ``cap`` entries).
        MUST stay byte-compatible with
        ``ray_tpu.serve.handle._RouterState._prefix_hashes`` over
        token-list routing keys — the router compares a request's
        hashes against these to find the replica with the longest
        cached prefix. Defensive copies everywhere: the engine thread
        mutates the tree while a replica RPC walks it, and a partial
        digest is a fine routing hint."""
        import hashlib

        def h64(b: bytes) -> int:
            return int.from_bytes(
                hashlib.blake2b(b, digest_size=8).digest(), "little")

        out: set = set()
        stack: List[Tuple[_Node, List[int]]] = [(self._root, [])]
        limit = max_chunks * chunk
        while stack and len(out) < cap:
            node, prefix = stack.pop()
            for child in list(node.children.values()):
                toks = prefix + [int(t) for t in child.key]
                for n_chunks in range(1, max_chunks + 1):
                    cut = n_chunks * chunk
                    if len(prefix) < cut <= len(toks):
                        out.add(h64(repr(tuple(toks[:cut])).encode()))
                if len(toks) < limit:
                    stack.append((child, toks))
        return sorted(out)[:cap]

    # -------------------------------------------------------------- misc
    def stats(self) -> Dict[str, int]:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_tokens": self.hit_tokens,
            "cow_hits": self.cow_hits,
            "cached_blocks": self._nodes,
            "cached_bytes": self.cached_bytes(),
            "inserted_blocks": self.inserted_blocks,
            "evicted_blocks": self.evicted_blocks,
            "rejected_inserts": self.rejected_inserts,
        }
