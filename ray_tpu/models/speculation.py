"""Speculative decoding subsystem: pluggable proposers + acceptance rule.

The serve engine (``ray_tpu.serve.llm``) turns every decode iteration
into a *verify* step over a K+1-token window per slot (see
``make_batched_spec_verify`` in :mod:`ray_tpu.models.decoding`): a
proposer guesses up to K next tokens per active slot, the target model
scores the whole window in one forward, and the standard rejection-
sampling rule accepts a prefix + one bonus token. Slots with no
proposal degenerate to a 1-token window — i.e. a plain decode step —
so speculation composes with continuous batching (per-slot windows,
admission/eviction between iterations) instead of the old
lone-greedy-stream special case.

Proposers (vLLM ``speculative_config`` parity, reference:
``python/ray/llm/_internal/serve/.../vllm_models.py``):

- ``ngram`` — prompt lookup: propose the k tokens that followed the most
  recent earlier occurrence of the trailing n-gram. No extra model, no
  device state.
- ``draft`` — a small Llama-family draft model runs in lockstep with the
  target: its own slot cache is prefilled on admission, advanced K
  greedy decode steps per proposal round, and rolled back to the
  accepted prefix after each verify (rows past the length are invisible,
  the same contract as the target cache). A slot the draft fell behind
  on (all-K acceptance consumes one token the draft never cached)
  catches up through the draft's own batched verify before proposing.

Acceptance (``accept_speculative``): proposals are deterministic given
the proposer state, i.e. a delta distribution q. For temperature 0 the
rule reduces to the argmax-chain comparison (token-identical to
non-speculative greedy decoding). For temperature > 0 the target
distribution is preserved exactly: token x is accepted with probability
p(x); on rejection the bonus token is resampled from the residual
max(0, p - q) — p with the rejected token zeroed out, renormalized.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

_METHODS = ("ngram", "draft")
# NB: no "enabled" here — disabling engine-level speculation is spelled
# speculation=None; per-request opt-out ({"enabled": False}) is a
# different surface (serve.llm._parse_req_spec)
_DICT_KEYS = {"method", "k", "ngram", "draft_model", "draft_config",
              "draft_params", "draft_seed"}


@dataclasses.dataclass(frozen=True)
class SpeculationConfig:
    """Canonical speculation config (engine kwarg / declarative spec).

    Accepted user forms (``parse``): a method string (``"ngram"`` /
    ``"draft"``) or a dict ``{"method": ..., "k": ..., "draft_model":
    ...}``. ``draft_model`` names a config in
    ``ray_tpu.models.llama.CONFIGS``; explicit ``draft_config`` /
    ``draft_params`` override it (tests and checkpoint loaders pass the
    real objects — they are not JSON-serializable, so declarative
    configs use ``draft_model``).
    """

    method: str = "ngram"
    k: int = 4
    ngram: int = 2
    draft_model: Optional[str] = None
    draft_config: Any = None
    draft_params: Any = None
    draft_seed: int = 1

    @classmethod
    def parse(cls, spec, default_k: int = 4) -> "SpeculationConfig":
        if isinstance(spec, SpeculationConfig):
            return spec
        if isinstance(spec, str):
            spec = {"method": spec}
        if not isinstance(spec, dict):
            raise ValueError(
                f"speculation must be a method string or dict, got "
                f"{type(spec).__name__}")
        unknown = set(spec) - _DICT_KEYS
        if unknown:
            raise ValueError(
                f"speculation has unknown fields {sorted(unknown)}; "
                f"known: {sorted(_DICT_KEYS)}")
        method = spec.get("method", "ngram")
        if method not in _METHODS:
            raise ValueError(
                f"speculation method {method!r}: one of {_METHODS}")
        k = int(spec.get("k", default_k))
        if k <= 0:
            raise ValueError("speculation k must be positive")
        ngram = int(spec.get("ngram", 2))
        if ngram <= 0:
            raise ValueError("speculation ngram must be positive")
        out = cls(method=method, k=k, ngram=ngram,
                  draft_model=spec.get("draft_model"),
                  draft_config=spec.get("draft_config"),
                  draft_params=spec.get("draft_params"),
                  draft_seed=int(spec.get("draft_seed", 1)))
        if method == "draft" and out.draft_model is None \
                and out.draft_config is None:
            raise ValueError(
                "speculation method 'draft' needs a draft_model name "
                "(ray_tpu.models.llama.CONFIGS) or an explicit "
                "draft_config/draft_params pair")
        return out

    def to_dict(self) -> Dict[str, Any]:
        """JSON-able canonical form (declarative config surface); drops
        the non-serializable explicit config/params fields."""
        out: Dict[str, Any] = {"method": self.method, "k": self.k}
        if self.method == "ngram":
            out["ngram"] = self.ngram
        if self.draft_model is not None:
            out["draft_model"] = self.draft_model
            out["draft_seed"] = self.draft_seed
        return out

    def build_proposer(self, target_config, *, num_slots: int,
                       max_seq: int):
        if self.method == "ngram":
            return NgramProposer(self.k, ngram=self.ngram)
        from ray_tpu.models import llama

        config = self.draft_config
        if config is None:
            if self.draft_model not in llama.CONFIGS:
                raise ValueError(
                    f"draft_model {self.draft_model!r}: not in "
                    f"{sorted(llama.CONFIGS)}")
            config = llama.CONFIGS[self.draft_model]
        if config.vocab_size != target_config.vocab_size:
            # reject before init_params: a real draft's parameter pytree
            # is seconds and GBs to build (DraftProposer re-checks)
            raise ValueError(
                f"draft/target tokenizer mismatch: draft vocab_size "
                f"{config.vocab_size} != target "
                f"{target_config.vocab_size} — speculation requires the "
                "models to share one tokenizer")
        params = self.draft_params
        if params is None:
            import jax

            params = llama.init_params(config,
                                       jax.random.key(self.draft_seed))
        return DraftProposer(target_config, config, params,
                             num_slots=num_slots, max_seq=max_seq,
                             k=self.k)


def make_length_installer():
    """Jitted fixed-shape cache-length installer,
    ``install(length, new, touched) -> where(touched, new, length)`` —
    ONE compiled program however many slots changed (used for both the
    target's and the draft's post-verify rollback; a variable-size
    ``.at[idx].set`` would recompile per distinct index-vector size)."""
    import jax
    import jax.numpy as jnp

    return jax.jit(
        lambda length, new, touched: jnp.where(touched, new, length))


def propose_ngram(context: List[int], k: int, ngram: int = 2):
    """Prompt-lookup proposal (vLLM "[ngram]" speculative method): find
    the most recent earlier occurrence of the trailing ``ngram`` tokens
    and propose the k tokens that followed it. None if no match."""
    if len(context) < ngram + 1 or k <= 0:
        return None
    tail = context[-ngram:]
    # scan right-to-left, excluding the trailing occurrence itself
    for i in range(len(context) - ngram - 1, -1, -1):
        if context[i:i + ngram] == tail:
            nxt = context[i + ngram:i + ngram + k]
            if nxt:
                return list(nxt)
            return None
    return None


class Proposer:
    """Per-slot proposal source driven by the engine loop.

    ``infos`` (propose) maps slot -> {"seq": prompt+output token list
    (the last entry is the pending token not yet in any cache),
    "target_len": tokens cached in the target's slot, "k": max proposals
    wanted for this slot this round (0 = plain decode)}.
    """

    def admit(self, slot: int, tokens: List[int]) -> None:
        """Slot was (re)admitted with ``tokens`` cached in the target."""

    def release(self, slot: int) -> None:
        """Slot finished or was evicted."""

    def propose(self, infos: Dict[int, dict]) -> Dict[int, List[int]]:
        raise NotImplementedError

    def after_verify(self, accepted: Dict[int, int]) -> None:
        """Per-slot accepted counts from the verify just run (slots that
        finished inside the window are included; release() follows)."""

    def stats(self) -> Dict[str, Any]:
        return {}


class NgramProposer(Proposer):
    """Prompt-lookup proposals per slot; no model, no device state."""

    def __init__(self, k: int, ngram: int = 2):
        self.k = k
        self.ngram = ngram

    def propose(self, infos: Dict[int, dict]) -> Dict[int, List[int]]:
        out = {}
        for slot, info in infos.items():
            prop = propose_ngram(info["seq"], info["k"], self.ngram)
            out[slot] = prop or []
        return out


class DraftProposer(Proposer):
    """Small-model proposals: the draft keeps its own slot cache in
    lockstep with the target (prefill on admission, K batched greedy
    decode steps per round, rollback to the accepted prefix after
    verify, batched-verify catch-up when it falls a token behind)."""

    def __init__(self, target_config, draft_config, draft_params, *,
                 num_slots: int, max_seq: int, k: int = 4):
        from ray_tpu.models.decoding import (
            init_cache, make_decode_step, make_kv_ingest, make_prefill)

        if draft_config.vocab_size != target_config.vocab_size:
            raise ValueError(
                f"draft/target tokenizer mismatch: draft vocab_size "
                f"{draft_config.vocab_size} != target "
                f"{target_config.vocab_size} — speculation requires the "
                "models to share one tokenizer")
        self.config = draft_config
        self.params = draft_params
        self.num_slots = num_slots
        self.max_seq = max_seq
        self.k = k
        self.cache = init_cache(draft_config, num_slots, max_seq)
        self._prefill = make_prefill(draft_params, draft_config)
        self._decode = make_decode_step(draft_params, draft_config)
        # KV-write-only catch-up: all-K-accepted rounds no longer pay a
        # discarded (slots, k+1, vocab) lm-head einsum (the round-7
        # "known draft-path optimization")
        self._ingest = make_kv_ingest(draft_params, draft_config)
        self._len = np.zeros(num_slots, np.int64)   # host mirror
        self._last_m: Dict[int, int] = {}           # proposals last round
        self.draft_steps = 0
        self._fix_len = make_length_installer()

    def admit(self, slot: int, tokens: List[int]) -> None:
        import jax.numpy as jnp

        from ray_tpu.models.decoding import pad_to_bucket

        P = min(pad_to_bucket(len(tokens)), self.max_seq)
        buf = np.zeros((1, P), np.int32)
        buf[0, :len(tokens)] = tokens
        self.cache, _ = self._prefill(self.cache, jnp.asarray(buf),
                                      len(tokens), slot)
        self._len[slot] = len(tokens)
        self._last_m.pop(slot, None)

    def release(self, slot: int) -> None:
        self._len[slot] = 0
        self._last_m.pop(slot, None)

    def _catch_up(self, infos: Dict[int, dict]) -> None:
        """Ingest sequence tokens the draft cache is missing (typically
        one, after an all-K acceptance) through the draft's batched
        verify — windows of up to C tokens per call."""
        import jax.numpy as jnp

        # FIXED window width: per-slot k shrinks near max_tokens/max_seq
        # and a varying width would compile one ingest program per size
        C = self.k + 1
        while True:
            missing = {}
            for slot, info in infos.items():
                # k == 0 slots (per-request opt-out, window out of room)
                # never propose, so keeping their draft cache current
                # would burn one ingest forward per engine iteration for
                # nothing; if k ever becomes positive again the gap is
                # ingested then
                if info["k"] <= 0:
                    continue
                have = int(self._len[slot])
                if have < info["target_len"]:
                    missing[slot] = info["seq"][have:info["target_len"]]
            if not missing:
                return
            buf = np.zeros((self.num_slots, C), np.int32)
            true_lens = np.zeros(self.num_slots, np.int32)
            starts = np.asarray(self._len, np.int32).copy()
            for slot, toks in missing.items():
                n = min(len(toks), C)
                buf[slot, :n] = toks[:n]
                true_lens[slot] = n
            self.cache = self._ingest(
                self.cache, jnp.asarray(buf), jnp.asarray(true_lens),
                jnp.asarray(starts))
            for slot in missing:
                self._len[slot] += int(true_lens[slot])

    def propose(self, infos: Dict[int, dict]) -> Dict[int, List[int]]:
        import jax.numpy as jnp

        self._last_m = {}
        if not infos:
            return {}
        self._catch_up(infos)
        props: Dict[int, List[int]] = {s: [] for s in infos}
        kmax = max(info["k"] for info in infos.values())
        feed = np.zeros(self.num_slots, np.int32)
        for slot, info in infos.items():
            feed[slot] = info["seq"][-1]
        for step in range(kmax):
            active = np.zeros(self.num_slots, bool)
            for slot, info in infos.items():
                active[slot] = info["k"] > step
            if not active.any():
                break
            self.cache, logits = self._decode(
                self.cache, jnp.asarray(feed), jnp.asarray(active))
            self.draft_steps += 1
            toks = np.asarray(logits).argmax(-1)
            for slot, info in infos.items():
                if info["k"] > step:
                    t = int(toks[slot])
                    props[slot].append(t)
                    feed[slot] = t
                    self._len[slot] += 1
        self._last_m = {s: len(p) for s, p in props.items()}
        return props

    def after_verify(self, accepted: Dict[int, int]) -> None:
        """Roll the draft cache back to the accepted prefix: rows
        [target_len, target_len + min(a+1, m)) hold the fed window
        tokens, all of which the accepted sequence kept; rejected rows
        sit past the new length and later writes overwrite them. An
        all-K acceptance leaves the draft one token short (the last
        proposal was never fed) — the next round's catch-up feeds it."""
        import jax.numpy as jnp

        touched = np.zeros(self.num_slots, bool)
        new_lens = np.zeros(self.num_slots, np.int32)
        for slot, a in accepted.items():
            m = self._last_m.get(slot, 0)
            if m == 0:
                continue
            pre = int(self._len[slot]) - m
            new = pre + min(a + 1, m)
            self._len[slot] = new
            touched[slot] = True
            new_lens[slot] = new
        if touched.any():
            self.cache["length"] = self._fix_len(
                self.cache["length"], jnp.asarray(new_lens),
                jnp.asarray(touched))

    def stats(self) -> Dict[str, Any]:
        return {"spec_draft_steps": self.draft_steps}


def _softmax(logits: np.ndarray, temperature: float) -> np.ndarray:
    z = logits.astype(np.float64) / max(temperature, 1e-5)
    z -= z.max()
    p = np.exp(z)
    return p / p.sum()


def accept_greedy(greedy: np.ndarray, proposal: List[int]) -> tuple:
    """Temperature-0 acceptance from precomputed argmax rows only.

    ``greedy``: (1+m,) argmax token per window position. Equivalent to
    ``accept_speculative(logits, proposal, 0.0, ...)`` but lets the
    engine ship (B, C) int32 ids off-device instead of the full
    (B, C, vocab) logits when no active slot samples."""
    m = len(proposal)
    a = 0
    while a < m and int(greedy[a]) == proposal[a]:
        a += 1
    return [int(t) for t in proposal[:a]] + [int(greedy[a])], a


def accept_speculative(logits: np.ndarray, proposal: List[int],
                       temperature: float, rng) -> tuple:
    """Apply the rejection-sampling acceptance rule to one slot's verify
    window.

    ``logits``: (1+m, vocab) target logits for window
    [pending_token, p_1..p_m]; row i is the target's next-token
    distribution AFTER window[0..i]. Returns ``(emitted, accepted)``
    where ``emitted`` is ``proposal[:accepted] + [bonus]`` (1..m+1
    tokens) and ``accepted`` counts proposal tokens kept.

    temperature 0: accept while the argmax chain matches (exact greedy
    equivalence). temperature > 0: proposals are deterministic (q is a
    delta), so token x is accepted with probability p(x) and the bonus
    resamples from the residual p with x zeroed, renormalized — the
    emitted stream is distributed exactly as non-speculative sampling.
    """
    m = len(proposal)
    if temperature <= 0.0:
        return accept_greedy(logits.argmax(-1), proposal)
    for i in range(m):
        probs = _softmax(logits[i], temperature)
        if rng.random() < probs[proposal[i]]:
            continue
        residual = probs.copy()
        residual[proposal[i]] = 0.0
        total = residual.sum()
        if total <= 0.0:
            # p was (numerically) a delta at the proposal yet it was
            # rejected — only reachable through float rounding; the
            # proposal token IS the sample then
            return [int(t) for t in proposal[:i + 1]], i
        bonus = int(rng.choice(residual.size, p=residual / total))
        return [int(t) for t in proposal[:i]] + [bonus], i
    probs = _softmax(logits[m], temperature)
    bonus = int(rng.choice(probs.size, p=probs))
    return [int(t) for t in proposal] + [bonus], m
